"""Continuous batching: slot-based serving loop over one batched decode step.

The serving counterpart of generate.py (torch-ecosystem analogues: vLLM's
continuous batching, TGI's router). generate() runs one batch lockstep —
every sequence prefills together and finishes together, so short requests
wait on long ones and free batch rows idle. This module keeps a fixed pool
of B cache SLOTS instead: requests are admitted into free slots as they
arrive, every active slot advances one token per batched step, and a slot
frees the moment its row emits EOS or exhausts its budget.

TPU-first shape discipline (SURVEY §7.4.5 — no dynamic shapes):
- The KV cache stays ONE static (B, max_seq_len, H_kv, D) buffer per layer.
  Per-row positions come from the model's ``decode_rows`` mode
  (models/llama.py): ``cache_index`` is (B,), rope/mask/update are per-row,
  so slots at different offsets share a single jitted step — two
  executables steady-state (prefill per bucket + the step), regardless of
  arrival order.
- Prompts prefill at B=1 padded to a power-of-two BUCKET (few compiles,
  bounded) and the resulting cache row is scattered into the slot
  (``_insert_row``). Right-padding is causal-safe: the last real token
  never attends to pad positions, and pad K/V beyond ``true_len`` stays
  masked (cache_index) until overwritten by real decode steps.
- Free slots keep decoding garbage rows — their outputs are ignored and
  their state is fully overwritten at the next admit. Masking them out
  would need a dynamic batch shape; computing them costs nothing extra in
  the batched step.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_train_tpu.config import ModelConfig, PrecisionConfig
from pytorch_distributed_train_tpu.generate import (
    _cache_shapes,
    _cache_shardings,
    _decode_step,
    build_decode_model,
    filter_logits,
    init_cache,
)


def load_params_for_serving(cfg, safetensors_path: str,
                            quantize: str = ""):
    """Load torch-layout safetensors weights for a prepared TrainConfig —
    the shape template comes from one eval_shape init (no real init), and
    ``quantize='int8'|'int4'`` converts to the weight-only quantized tree
    (int4: group-wise scales, half int8's HBM — quant.quantize_leaf_int4).
    Shared by tools/generate_cli.py and tools/serve_http.py so the loading
    pipeline cannot diverge between the two entrypoints."""
    from pytorch_distributed_train_tpu import quant
    from pytorch_distributed_train_tpu.interop import load_flax_safetensors
    from pytorch_distributed_train_tpu.models.registry import build_model

    is_t5 = cfg.model.name.startswith("t5")
    init_inputs = ((jnp.zeros((1, 2), jnp.int32),) * 2 if is_t5
                   else (jnp.zeros((1, 2), jnp.int32),))
    template = jax.eval_shape(
        lambda: build_model(cfg.model, cfg.precision).init(
            {"params": jax.random.PRNGKey(0)}, *init_inputs,
            train=False))["params"]
    params = load_flax_safetensors(safetensors_path, template)
    if quantize:
        params = jax.jit(
            lambda p: quant.quantize_tree_named(p, quantize))(params)
    return params


def trim_at_eos(tokens: list[int], eos_id: int | None) -> list[int]:
    """Cut a generated continuation at its first EOS (exclusive) — THE
    eos-trim rule shared by every serving entrypoint (generate CLI, HTTP
    server, chat REPL)."""
    if eos_id is not None and eos_id in tokens:
        return tokens[: tokens.index(eos_id)]
    return tokens


def build_serving_model(model_cfg: ModelConfig, precision: PrecisionConfig):
    """The continuous-batching twin of a decode model: per-row cache
    offsets enabled (models/llama.py decode_rows)."""
    model = build_decode_model(model_cfg, precision)
    if not any(f.name == "decode_rows"
               for f in dataclasses.fields(model)):
        raise ValueError(
            f"model {model_cfg.name!r} has no decode_rows mode (continuous "
            "batching covers the llama and gpt2 families)")
    return dataclasses.replace(model, decode_rows=True)


@partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def _prefill_step(model, params, cache, ids, true_len):
    """Prefill a right-padded (1, P) prompt; return the logits at the last
    REAL token (position true_len-1, not P-1) and the filled cache."""
    from pytorch_distributed_train_tpu import quant

    params = quant.dequantize_tree(params, model.dtype)
    logits, updated = model.apply(
        {"params": params, "cache": cache}, ids, train=False,
        mutable=["cache"],
    )
    last = jnp.take_along_axis(
        logits, (true_len - 1)[:, None, None], axis=1)[:, 0]
    return last, updated["cache"]


@partial(jax.jit, donate_argnums=(0,))
def _insert_row(big_cache, row_cache, r, true_len):
    """Scatter a freshly prefilled B=1 cache into slot ``r`` of the pool.

    K/V leaves copy the FULL row (zeros beyond the prompt erase the
    previous occupant); the (B,) index counters — cache_index, and gpt2's
    pos_index — set slot r to the prompt's true length (the prefill wrote
    the padded length)."""
    def one(big, row):
        if big.ndim >= 2:  # (B, L, H, D) K/V buffers
            return jax.lax.dynamic_update_slice(
                big, row.astype(big.dtype),
                (r,) + (0,) * (big.ndim - 1))
        return big.at[r].set(true_len.astype(big.dtype))  # (B,) index

    return jax.tree.map(one, big_cache, row_cache)


@jax.jit
def _gather_row(big_cache, r):
    """Extract slot ``r`` as a B=1 cache tree (the inverse of
    _insert_row) — session resume runs its multi-token continuation on
    the extracted row, then scatters it back."""
    return jax.tree.map(
        lambda x: jax.lax.dynamic_slice_in_dim(x, r, 1, 0), big_cache)


@partial(jax.jit, donate_argnums=(0,))
def _set_row_index(row_cache, pos):
    """Pin a B=1 cache's position counters (cache_index, gpt2's
    pos_index) to ``pos``: a PARKED row's counters free-ran while other
    slots decoded (its garbage writes stay masked/overwritten — see
    ContinuousBatcher session notes), so resume re-anchors them before
    ingesting the next turn."""
    return jax.tree.map(
        lambda x: jnp.full_like(x, pos) if x.ndim == 1 else x, row_cache)


@partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def _decode_multi_logits(model, params, cache, ids):
    """Batched multi-token continuation returning ALL positions' logits
    (B, S, V) — the speculative verify step (``_decode_step`` keeps only
    the last position, which is all plain decode needs)."""
    from pytorch_distributed_train_tpu import quant

    params = quant.dequantize_tree(params, model.dtype)
    logits, updated = model.apply(
        {"params": params, "cache": cache}, ids, train=False,
        mutable=["cache"],
    )
    return logits, updated["cache"]


@partial(jax.jit, donate_argnums=(0,))
def _set_row_indices(cache, idx_vec):
    """Vector form of _set_row_index: pin EVERY row's position counters
    (cache_index, gpt2's pos_index) to its own value — the per-row
    speculative rollback (rows rewind to pending + accepted prefix;
    parked/dead rows' values are don't-cares, same masking discipline as
    free-running counters)."""
    return jax.tree.map(
        lambda x: idx_vec.astype(x.dtype) if x.ndim == 1 else x, cache)


def _spec_accept_core(raw_logits, eff_logits, rng, temperature, drafts,
                      top_p, min_p, seeds, ntok, top_k: int):
    """Per-row prompt-lookup acceptance over a batched (B, k+1) verify.

    raw_logits: (B, k+1, V) — position j is the distribution AFTER
    ingesting input column j (col 0 = the row's pending token, cols
    1..k = the draft proposals), so drafts[:, i] is scored by position
    i. eff_logits is the law actually sampled from — equal to
    raw_logits on the plain path, penalty/bias-adjusted per position on
    the penalized path (counts advance per accepted draft — the
    cumulative one-hots in _spec_verify_rows_penalized).
    Point-mass draft law (speculative.prompt_lookup_generate): accept
    d_i with prob p_t(d_i) (greedy rows: iff d_i is the argmax of the
    effective law), residual = p_t with d_i zeroed. Mixed greedy/
    sampled rows resolve by traced temperature. Returns (n, nxt,
    d_logp, nxt_logp): accepted count (B,), the resample/bonus token
    (B,), and RAW-distribution logprobs for the drafts (B, k) and nxt
    (B,) — the logprobs contract matches the plain samplers (raw
    pre-penalty distribution, comparable across requests)."""
    B, k1, V = raw_logits.shape
    k = k1 - 1
    raw_logp = jax.nn.log_softmax(raw_logits.astype(jnp.float32), axis=-1)
    logits = eff_logits.astype(jnp.float32)
    t_choice = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (B, k+1)
    greedy = (temperature == 0.0)

    f = filter_logits(logits,
                      jnp.maximum(temperature, 1e-6)[:, None, None],
                      top_k, top_p[:, None, None], min_p[:, None, None])
    p_t = jax.nn.softmax(f, axis=-1)
    p_t_k = p_t[:, :k]
    p_t_tok = jnp.take_along_axis(p_t_k, drafts[:, :, None],
                                  axis=-1)[:, :, 0]  # (B, k)

    keys = _row_keys(rng, seeds, ntok)
    k3 = jax.vmap(lambda kk: jax.random.split(kk, 3))(keys)  # (B, 3, 2)
    u = jax.vmap(lambda kk: jax.random.uniform(kk, (k,)))(k3[:, 0])
    accept = jnp.where(greedy[:, None],
                       t_choice[:, :k] == drafts,
                       u < p_t_tok)
    n = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=-1), axis=-1)

    row = jnp.minimum(n, k - 1)
    p_rej = jnp.take_along_axis(
        p_t_k, row[:, None, None], axis=1)[:, 0]  # (B, V)
    d_rej = jnp.take_along_axis(drafts, row[:, None], axis=1)[:, 0]
    residual = p_rej.at[jnp.arange(B), d_rej].set(0.0)
    mass = jnp.sum(residual, axis=-1, keepdims=True)
    residual = jnp.where(mass > 0, residual / jnp.maximum(mass, 1e-20),
                         p_rej)
    resampled = jax.vmap(
        lambda kk, pr: jax.random.categorical(
            kk, jnp.log(jnp.maximum(pr, 1e-30)))
    )(k3[:, 1], residual).astype(jnp.int32)
    bonus = jax.vmap(
        lambda kk, pb: jax.random.categorical(
            kk, jnp.log(jnp.maximum(pb, 1e-30)))
    )(k3[:, 2], p_t[:, k]).astype(jnp.int32)
    nxt_sampled = jnp.where(n < k, resampled, bonus)
    nxt_greedy = jnp.take_along_axis(t_choice, n[:, None], axis=1)[:, 0]
    nxt = jnp.where(greedy, nxt_greedy, nxt_sampled).astype(jnp.int32)

    d_logp = jnp.take_along_axis(raw_logp[:, :k], drafts[:, :, None],
                                 axis=-1)[:, :, 0]  # (B, k)
    nxt_row = jnp.take_along_axis(raw_logp, n[:, None, None],
                                  axis=1)[:, 0]  # (B, V)
    nxt_logp = jnp.take_along_axis(nxt_row, nxt[:, None], axis=-1)[:, 0]
    return n, nxt, d_logp, nxt_logp


@partial(jax.jit, static_argnums=(8,))
def _spec_verify_rows(logits, rng, temperature, drafts, top_p, min_p,
                      seeds, ntok, top_k: int):
    """Plain-path speculative acceptance: effective law == raw law."""
    return _spec_accept_core(logits, logits, rng, temperature, drafts,
                             top_p, min_p, seeds, ntok, top_k)


@partial(jax.jit, static_argnums=(14,))
def _spec_verify_rows_penalized(logits, rng, temperature, drafts,
                                counts, gen_counts, rep, pres, freq,
                                bias, top_p, min_p, seeds, ntok,
                                top_k: int):
    """Speculative acceptance under per-row context penalties + logit
    bias: the SAME adjustment the lockstep penalized sampler applies,
    per verify position, with counts ADVANCED per accepted draft.

    The subtlety: position i's target law must score a context in which
    drafts 0..i-1 were already committed (that is the sequence the row
    would have walked token-by-token). Cumulative one-hots of the draft
    tokens shift both count tensors per position — positions past the
    first rejection are dead (cumprod acceptance) so their laws being
    "wrong about the future" is irrelevant, and the residual/bonus rows
    (position n) see exactly the n accepted drafts. This makes greedy
    penalized spec-serving token-for-token equal to penalized lockstep
    decoding, and sampled rows exact w.r.t. the penalized law.

    bias: scalar 0.0 (no biased row) or (B, V) — broadcast over the
    k+1 verify positions (logit_bias is context-free, so it does not
    advance)."""
    from pytorch_distributed_train_tpu.generate import apply_penalties

    B, k1, V = logits.shape
    k = k1 - 1
    oh = jax.nn.one_hot(drafts, V, dtype=jnp.float32)  # (B, k, V)
    # cum[:, i] = one-hots of drafts 0..i-1 (position 0 sees none)
    cum = jnp.concatenate(
        [jnp.zeros((B, 1, V), jnp.float32),
         jnp.cumsum(oh, axis=1)], axis=1)  # (B, k+1, V)
    counts_i = counts[:, None, :] + cum
    gen_i = gen_counts[:, None, :] + cum
    eff = jax.vmap(
        lambda lg, c, g: apply_penalties(
            lg, c, gen_counts=g, repetition_penalty=rep,
            presence_penalty=pres, frequency_penalty=freq),
        in_axes=(1, 1, 1), out_axes=1)(logits, counts_i, gen_i)
    eff = eff + (bias if jnp.ndim(bias) == 0 else bias[:, None, :])
    return _spec_accept_core(logits, eff, rng, temperature, drafts,
                             top_p, min_p, seeds, ntok, top_k)


def _row_keys(rng, seeds, ntok):
    """Per-row sampling keys: seeded rows (seed >= 0) use their own
    deterministic chain fold_in(PRNGKey(seed), tokens_generated) — output
    reproducible regardless of batch composition or slot assignment;
    unseeded rows fold the shared per-step key by row index."""
    seeded = jax.vmap(
        lambda s, n: jax.random.fold_in(
            jax.random.PRNGKey(s.astype(jnp.uint32)), n)
    )(seeds, ntok)
    shared = jax.vmap(lambda i: jax.random.fold_in(rng, i))(
        jnp.arange(seeds.shape[0]))
    return jnp.where((seeds >= 0)[:, None], seeded, shared)


def _sample_filtered(f, keys):
    return jax.vmap(
        lambda k, row: jax.random.categorical(k, row)
    )(keys, f).astype(jnp.int32)


@partial(jax.jit, static_argnums=(13,))
def _sample_rows_penalized(logits, rng, temperature, counts, gen_counts,
                           rep, pres, freq, bias, top_p, min_p, seeds,
                           ntok, top_k: int):
    """_sample_rows with per-row context penalties applied to the raw
    logits first (generate.apply_penalties — counts: prompt+generated
    for repetition; gen_counts: generated-only for the OpenAI additive
    penalties). The returned logprob stays the RAW pre-penalty
    distribution — comparable across requests regardless of their
    penalty settings (same contract as temperature)."""
    from pytorch_distributed_train_tpu.generate import apply_penalties

    raw_logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    penalized = apply_penalties(logits, counts, gen_counts=gen_counts,
                                repetition_penalty=rep,
                                presence_penalty=pres,
                                frequency_penalty=freq) + bias
    greedy = jnp.argmax(penalized, axis=-1).astype(jnp.int32)
    f = filter_logits(penalized, jnp.maximum(temperature, 1e-6)[:, None],
                      top_k, top_p[:, None], min_p[:, None])
    sampled = _sample_filtered(f, _row_keys(rng, seeds, ntok))
    tok = jnp.where(temperature == 0.0, greedy, sampled)
    lp = jnp.take_along_axis(raw_logp, tok[:, None], axis=-1)[:, 0]
    return tok, lp


@partial(jax.jit, static_argnums=(7,))
def _sample_rows(logits, rng, temperature, top_p, min_p, seeds, ntok,
                 top_k: int):
    """Per-row sampling: rows with temperature 0 are greedy, others sample
    at their own temperature under PER-ROW top-p/min-p (traced (B,)
    operands — OpenAI requests carry top_p, so it cannot be a static
    recompile-per-value arg; out-of-range entries disable per row), with
    PER-ROW keys (seeded requests reproduce independently of batch
    composition — _row_keys) and a server-wide static top-k. Also returns
    each emitted token's log-probability under the RAW model distribution
    (pre-temperature/filtering — comparable across requests regardless of
    their sampling settings)."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    f = filter_logits(logits, jnp.maximum(temperature, 1e-6)[:, None],
                      top_k, top_p[:, None], min_p[:, None])
    sampled = _sample_filtered(f, _row_keys(rng, seeds, ntok))
    tok = jnp.where(temperature == 0.0, greedy, sampled)
    raw_logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    lp = jnp.take_along_axis(raw_logp, tok[:, None], axis=-1)[:, 0]
    return tok, lp


def _ngram_build(ctx: list[int], ngram: int) -> dict:
    """Index every ``ngram``-gram of ``ctx`` to its (latest, previous)
    start positions. The incremental replacement for
    speculative.propose_from_context's full backward rescan: the tail's
    own occurrence is always the latest insert, so (latest, previous)
    is exactly enough to answer "most recent occurrence STRICTLY before
    the tail" — the rescan's semantics — in O(1)."""
    idx: dict = {}
    for i in range(len(ctx) - ngram + 1):
        key = tuple(ctx[i:i + ngram])
        prev = idx.get(key)
        idx[key] = (i, None if prev is None else prev[0])
    return idx


def _ngram_append(ctx: list[int], idx: dict, tok: int,
                  ngram: int) -> None:
    """O(1) per committed token: append and index the one new ngram."""
    ctx.append(tok)
    if len(ctx) >= ngram:
        i = len(ctx) - ngram
        key = tuple(ctx[i:])
        prev = idx.get(key)
        idx[key] = (i, None if prev is None else prev[0])


def _ngram_propose(ctx: list[int], idx: dict, ngram: int,
                   k: int) -> list[int] | None:
    """Index-backed prompt-lookup proposal — same result, token for
    token, as speculative.propose_from_context(ctx, k, ngram), without
    the O(context) rescan per row per round."""
    if len(ctx) <= ngram:
        return None
    ent = idx.get(tuple(ctx[-ngram:]))
    if ent is None:
        return None
    latest, prev = ent
    pos = prev if latest == len(ctx) - ngram else latest
    if pos is None:
        return None
    follow = ctx[pos + ngram: pos + ngram + k]
    if not follow:
        return None
    return follow + [follow[-1]] * (k - len(follow))


@dataclasses.dataclass
class Request:
    uid: int
    prompt: list[int]
    max_new_tokens: int
    temperature: float = 0.0
    eos_id: int | None = None
    keep: bool = False          # park the slot on finish (chat sessions)
    session: int | None = None  # continue a parked session's cache
    # FORK a parked entry instead of consuming it: the request copies the
    # parked row (shared-prefix cache — e.g. one preloaded system prompt
    # serving many requests) into a free slot; the template survives.
    prefix: int | None = None
    # Context-aware logit penalties (generate.apply_penalties — HF CTRL
    # rule + the OpenAI additive pair). Scope: THIS request's prompt +
    # its generated tokens (a resumed session's earlier turns are not
    # re-counted — they live only as KV).
    repetition_penalty: float = 1.0
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    # OpenAI logit_bias ({token_id: bias in [-100, 100]}), added to raw
    # logits after penalties, before the warpers.
    logit_bias: dict | None = None
    # Per-request nucleus / min-p (OpenAI requests carry top_p): None →
    # the batcher's server-wide default. Traced per-row operands — no
    # recompile per value; top_k stays server-wide (static in the jit).
    top_p: float | None = None
    min_p: float | None = None
    # Per-request rng seed (OpenAI `seed`): a seeded request samples from
    # its OWN key chain fold_in(PRNGKey(seed), tokens_generated_so_far),
    # so its output is reproducible and INDEPENDENT of batch composition
    # (what else is in flight, which slot it landed in). None → the
    # batcher's shared per-step stream.
    seed: int | None = None


@dataclasses.dataclass
class Completion:
    uid: int
    prompt: list[int]
    tokens: list[int]  # generated continuation (includes eos if emitted)
    finish_reason: str  # "eos" | "length"
    # Session handle when the request ran with keep=True: pass as
    # submit(session=...) to continue this conversation from its resident
    # KV cache (no re-prefill of the earlier turns).
    session: int | None = None
    # Per-token log-probability of each generated token under the RAW
    # model distribution (parallel to ``tokens``).
    logprobs: list[float] = dataclasses.field(default_factory=list)


class ContinuousBatcher:
    """Slot-based continuous batching over ``slots`` concurrent sequences.

    Usage::

        b = ContinuousBatcher(cfg, precision, params, slots=8)
        b.submit([1, 2, 3], max_new_tokens=32)
        b.submit([4, 5], max_new_tokens=8, temperature=0.7)
        for completion in b.run():
            ...

    ``step()`` is the scheduler quantum: admit queued requests into free
    slots (one B=1 bucketed prefill each), then advance every slot one
    token in a single batched decode step. Sampling law matches
    generate(): greedy at temperature 0, categorical over
    temperature-scaled top-k/top-p-filtered logits otherwise
    (generate.filter_logits — temperature is per-request, top-k/top-p
    are batcher-wide).
    """

    _count_prompt = True  # penalties count the prompt (causal-LM context)

    supports_sessions = True  # multi-turn KV reuse (causal families)

    def __init__(self, model_cfg: ModelConfig, precision: PrecisionConfig,
                 params: Any, *, slots: int = 4, top_k: int = 0,
                 top_p: float = 0.0, min_p: float = 0.0, rng=None,
                 min_bucket: int = 16, mesh=None,
                 auto_prefix_min: int = 0,
                 spec_k: int = 0, spec_ngram: int = 3):
        self._init_common(params, slots, top_k, top_p, rng, min_p,
                          auto_prefix_min)
        # Prompt-lookup SPECULATIVE serving (opt-in): every batched step
        # verifies k proposals per row copied from the row's own history
        # (speculative.propose_from_context) in one (slots, k+1) forward
        # — per-row acceptance, per-row cache rollback. The k+1-token
        # verify reads the weights once, like a 1-token step, so rounds
        # that accept are nearly free and rounds that reject cost a
        # plain step. Exact-sampling law (point-mass drafts), including
        # penalized/biased rows: the penalized accept kernel advances
        # the count context per accepted draft, so its output law is
        # identical to the penalized lockstep path.
        if spec_k < 0 or (spec_k > 0 and spec_ngram < 1):
            raise ValueError(
                f"need spec_k >= 0 and spec_ngram >= 1, got "
                f"{spec_k}, {spec_ngram}")
        self.spec_k = spec_k
        self.spec_ngram = spec_ngram
        self.mesh = mesh
        self.model = self._build_batched_model(model_cfg, precision)
        # session resume ingests multi-token turns at per-row offsets
        self._model_multi = dataclasses.replace(self.model,
                                                decode_multi=True)
        self.cache = self._alloc_cache(slots)
        self.max_seq_len = self.model.max_seq_len
        self._build_buckets(self.max_seq_len, min_bucket)
        self._init_slot_state(slots)

    def _build_batched_model(self, model_cfg, precision):
        """The model the batched decode step runs (paged subclass adds
        the pool/table flags here)."""
        return build_serving_model(model_cfg, precision)

    def _alloc_cache(self, batch: int):
        """Zeroed KV cache for ``batch`` rows — allocated DIRECTLY into
        its mesh layout under multi-chip serving (``mesh=``: params came
        from generate.shard_decode_params; cache heads live beside their
        q/k/v columns on 'tensor', same as generate(mesh=)). GSPMD then
        propagates the layouts through the unchanged jitted steps."""
        if self.mesh is None:
            return init_cache(self.model, batch)
        # device_put, not a per-call jit: a fresh jit(lambda) here would
        # retrace+recompile on EVERY admission (jit caches key on the
        # function object) — admission must stay compile-free steady-state
        shapes = _cache_shapes(self.model, batch)
        zeros = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        return jax.device_put(zeros, _cache_shardings(self.mesh, shapes))

    def _init_common(self, params, slots, top_k, top_p, rng,
                     min_p: float = 0.0,
                     auto_prefix_min: int = 0) -> None:
        self.params = params
        self.slots = slots
        self.top_k = top_k
        self.top_p = top_p
        self.min_p = min_p
        # >0: submit() auto-forks from a preloaded template of >= this
        # many tokens when it prefixes the prompt (explicit prefix= and
        # sessions always win; 0 disables)
        self.auto_prefix_min = auto_prefix_min
        self.spec_k = 0  # causal batcher may enable; seq2seq never
        self.rng = rng if rng is not None else jax.random.PRNGKey(0)

    def _build_buckets(self, cap: int, min_bucket: int) -> None:
        # power-of-two prefill buckets bound compile count to
        # log2(cap / min_bucket) + 1 prefill executables
        self.buckets = []
        b = min_bucket
        while b < cap:
            self.buckets.append(b)
            b *= 2
        self.buckets.append(cap)

    def _init_slot_state(self, slots: int) -> None:
        self.queue: deque[Request] = deque()
        self._next_uid = 0
        # host-side slot state
        self._req: list[Request | None] = [None] * slots
        self._generated: list[list[int]] = [[] for _ in range(slots)]
        self._logprobs: list[list[float]] = [[] for _ in range(slots)]
        self._pending = np.zeros(slots, np.int32)  # next input token per slot
        self._temp = np.zeros(slots, np.float32)
        # per-slot penalty settings + (slots, V) context token counts
        # (host-side; shipped to the device only on penalized steps)
        self._rep = np.ones(slots, np.float32)
        self._pres = np.zeros(slots, np.float32)
        self._freq = np.zeros(slots, np.float32)
        # per-row nucleus/min-p (request override of the server default)
        self._top_p = np.full(slots, self.top_p, np.float32)
        self._min_p = np.full(slots, self.min_p, np.float32)
        # per-row request seed (-1 = unseeded: shared per-step stream)
        self._seed = np.full(slots, -1, np.int64)
        # seeded-chain offset: a preempted-and-requeued request resumes
        # its fold_in(PRNGKey(seed), ntok) chain where it left off —
        # ntok shipped to the samplers is base + len(generated)
        self._ntok_base = np.zeros(slots, np.int32)
        self._counts = np.zeros((slots, self.model.vocab_size),
                                np.float32)
        # generated-only counts: the OpenAI presence/frequency context
        # (prompt tokens feed _counts — the repetition context — only)
        self._gen_counts = np.zeros((slots, self.model.vocab_size),
                                    np.float32)
        self._bias = np.zeros((slots, self.model.vocab_size), np.float32)
        self._has_bias = np.zeros(slots, bool)  # O(slots) routing flag
        self._pos = np.zeros(slots, np.int64)  # tokens INGESTED per slot
        # sids shielded from LRU eviction while their fork is mid-
        # admission (see _evict_lru_parked)
        self._evict_protect: set[int] = set()
        # parked chat sessions: sid -> (slot, ingested pos, last token).
        # A parked row's K/V stays resident while other slots decode: its
        # counters free-run and each step writes ONE garbage K/V at its
        # running offset, but every such position is beyond the pinned
        # resume index (masked) and is overwritten by real tokens before
        # the mask ever exposes it — same discipline as dead rows.
        self._parked: dict[int, tuple[int, int, int | None]] = {}
        self._parked_slots: set[int] = set()
        # preload-template token registry (auto_prefix_min matching)
        self._template_tokens: dict[int, list[int]] = {}
        # speculative proposal context: per-slot token list (this
        # request's prompt + generated) + its incremental ngram index
        # (_ngram_build/_ngram_append) — maintained only when spec_k > 0
        self._ctx: list[list[int]] = [[] for _ in range(slots)]
        self._ngram_idx: list[dict] = [{} for _ in range(slots)]
        # host_ms/device_ms: wall-clock split of the decode loop —
        # host_ms is Python scheduling + proposal building + commit
        # bookkeeping, device_ms the dispatch-to-materialization block
        # (the np.asarray sync). admit_ms is the mixed admission span
        # (queue handling + prefill compute). The split makes a
        # host-bound serving loop (e.g. proposal scans at long
        # contexts) visible instead of silently eroding throughput.
        self.stats = {"steps": 0, "prefills": 0, "preloads": 0,
                      "resumes": 0, "forks": 0, "generated_tokens": 0,
                      "slot_token_slots": 0, "auto_prefix_hits": 0,
                      "host_ms": 0.0, "device_ms": 0.0, "admit_ms": 0.0}

    # ------------------------------------------------------------- intake
    def submit(self, prompt, max_new_tokens: int, *,
               temperature: float = 0.0, eos_id: int | None = None,
               keep: bool = False, session: int | None = None,
               prefix: int | None = None,
               repetition_penalty: float = 1.0,
               presence_penalty: float = 0.0,
               frequency_penalty: float = 0.0,
               logit_bias: dict | None = None,
               top_p: float | None = None,
               min_p: float | None = None,
               seed: int | None = None) -> int:
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if repetition_penalty <= 0.0:
            raise ValueError("repetition_penalty must be > 0 (1.0 = off)")
        for name, val in (("top_p", top_p), ("min_p", min_p)):
            if val is not None and not 0.0 <= val <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {val}")
        if seed is not None and not 0 <= int(seed) < 2**32:
            # _row_keys builds PRNGKey(seed mod 2^32): out-of-range seeds
            # would silently alias (and negatives would collide with the
            # internal -1 unseeded sentinel) — make it explicit instead.
            raise ValueError(
                f"seed must be in [0, 2**32), got {seed}")
        if logit_bias:
            from pytorch_distributed_train_tpu.generate import (
                validate_logit_bias,
            )

            validate_logit_bias(logit_bias, self.model.vocab_size)
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens} "
                "(admission always samples the first continuation token)")
        if ((keep or session is not None or prefix is not None)
                and not self.supports_sessions):
            raise ValueError(
                f"{type(self).__name__} does not support chat sessions")
        if session is not None and prefix is not None:
            raise ValueError("session= (consume) and prefix= (fork) are "
                             "mutually exclusive")
        if (self.auto_prefix_min > 0 and session is None
                and prefix is None and repetition_penalty == 1.0):
            # Automatic prefix cache: fork from the LONGEST still-parked
            # preloaded template that strictly prefixes this prompt (the
            # remainder must be non-empty — fork ingest needs a token).
            # Kept sessions never match (only preload() registers), and
            # explicit prefix=/session= win by the guard above.
            # repetition_penalty != 1.0 BYPASSES the match: the rewrite
            # truncates the request's penalty context to the remainder,
            # so the same request would sample from different
            # distributions depending on cache state (the nondeterminism
            # force_full_prompt exists to avoid). Presence/frequency
            # count generated tokens only and logit_bias is context-free
            # — only repetition needs the bypass.
            best, best_len = None, 0
            for sid, toks in self._template_tokens.items():
                n = len(toks)
                if (sid in self._parked and n >= self.auto_prefix_min
                        and best_len < n < len(prompt)
                        and prompt[:n] == toks):
                    best, best_len = sid, n
            if best is not None:
                prefix, prompt = best, prompt[best_len:]
                self.stats["auto_prefix_hits"] += 1
        ref = session if session is not None else prefix
        if ref is not None:
            if ref not in self._parked:
                raise ValueError(
                    f"unknown session {ref} (never kept/preloaded, "
                    "already resumed, or evicted under slot pressure)")
            _, pos, last_tok = self._parked[ref]
            # continuation ingests [last unconsumed token +] prompt
            extra = 0 if last_tok is None else 1
            # spec margin: a verify step writes spec_k+1 entries from the
            # row's position — without headroom the clamped dynamic
            # update would silently corrupt the tail slots
            margin = getattr(self, "spec_k", 0)
            if (pos + extra + len(prompt) + max_new_tokens + margin
                    > self.max_seq_len):
                raise ValueError(
                    f"session at position {pos} + turn ({len(prompt)}) + "
                    f"max_new_tokens ({max_new_tokens}) + spec margin "
                    f"({margin}) exceeds max_seq_len ({self.max_seq_len})")
        else:
            self._check_request(
                len(prompt),
                max_new_tokens + getattr(self, "spec_k", 0))
        uid = self._next_uid
        self._next_uid += 1
        self.queue.append(Request(uid, prompt, max_new_tokens,
                                  temperature, eos_id, keep=keep,
                                  session=session, prefix=prefix,
                                  repetition_penalty=repetition_penalty,
                                  presence_penalty=presence_penalty,
                                  frequency_penalty=frequency_penalty,
                                  logit_bias=logit_bias,
                                  top_p=top_p, min_p=min_p,
                                  seed=None if seed is None
                                  else int(seed)))
        return uid

    def preload(self, prompt) -> int:
        '''Prefill ``prompt`` into a slot and park it WITHOUT
        generating: a shared-prefix template (e.g. a system prompt).
        Serve from it with ``submit(user_turn, n, prefix=sid)`` — each
        such request FORKS the resident rows into its own slot, so one
        preload amortizes across any number of requests. Consumes one
        slot until evicted (LRU, like kept sessions).'''
        if not self.supports_sessions:
            raise ValueError(
                f"{type(self).__name__} does not support chat sessions")
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        # Spec headroom: _spec_step re-pins EVERY row (templates included)
        # to _pos each round, so each verify writes spec_k+1 K/V entries
        # starting AT the template's length — without this margin the
        # clamped dynamic update would slide those garbage writes back
        # INTO the template's real content.
        margin = getattr(self, "spec_k", 0)
        if len(prompt) + margin + 1 > self.max_seq_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + spec margin ({margin + 1}) "
                f"exceeds max_seq_len ({self.max_seq_len})")
        r = self._free_slot()
        if r is None:
            raise RuntimeError(
                "no slot available for preload (all active or reserved "
                "by sessions with queued continuations)")
        self._prefill_into(r, prompt)
        # Host-side position mirrors the cache_index _prefill_into pinned:
        # _spec_step's final _set_row_indices rewinds ALL rows to _pos, so
        # a stale _pos here would rewind the template into its own content
        # and every verify round would overwrite real K/V.
        self._pos[r] = len(prompt)
        self.stats["preloads"] += 1  # a prefill that admits NO token
        sid = self._next_uid
        self._next_uid += 1
        self._parked[sid] = (r, len(prompt), None)  # no unconsumed token
        self._parked_slots.add(r)
        # token registry for auto_prefix_min matching (templates only —
        # kept SESSIONS never auto-match: their content is a
        # conversation, not a shared prefix)
        self._template_tokens[sid] = list(prompt)
        return sid

    def _check_request(self, prompt_len: int, max_new_tokens: int) -> None:
        if prompt_len + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt ({prompt_len}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_seq_len "
                f"({self.max_seq_len})")

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"prompt length {n} exceeds max bucket")

    # ------------------------------------------------- row-cache hooks
    # The B=1 prefill/continuation machinery runs on a DENSE row cache
    # in every batcher; only how a finished row lands in (and is read
    # back out of) the batched pool differs. The paged subclass
    # overrides these five hooks; the scheduler above them is shared.
    @property
    def _row_model(self):
        return self.model

    @property
    def _row_model_multi(self):
        return self._model_multi

    def _alloc_row_cache(self):
        return self._alloc_cache(1)

    def _install_row(self, r: int, row_cache, true_len: int) -> None:
        """Land a freshly prefilled B=1 row cache in slot ``r``."""
        self.cache = _insert_row(self.cache, row_cache, jnp.int32(r),
                                 jnp.int32(true_len))

    def _extract_row(self, r: int, pos: int):
        """Slot ``r`` as a B=1 dense row cache with counters pinned to
        ``pos`` (session resume / template fork read path)."""
        row = _gather_row(self.cache, jnp.int32(r))
        return _set_row_index(row, jnp.int32(pos))

    def _install_row_range(self, r: int, row_cache, pos: int,
                           T: int) -> None:
        """Land a continued row back in slot ``r`` with ``T`` new
        tokens ingested at offset ``pos``."""
        self.cache = _insert_row(self.cache, row_cache, jnp.int32(r),
                                 jnp.int32(pos + T))

    # ---------------------------------------------------------- scheduler
    def _prefill_into(self, r: int, prompt: list[int]):
        """Bucket-padded B=1 prefill scattered into slot ``r``; returns
        the last-real-token logits. Shared by request admission and
        template preloading."""
        P = self._bucket(len(prompt))
        ids = np.zeros((1, P), np.int32)
        ids[0, : len(prompt)] = prompt
        row_cache = self._alloc_row_cache()
        last, row_cache = _prefill_step(
            self._row_model, self.params, row_cache, jnp.asarray(ids),
            jnp.asarray([len(prompt)], jnp.int32))
        self._install_row(r, row_cache, len(prompt))
        self.stats["prefills"] += 1
        return last

    def _admit(self, r: int, req: Request) -> Completion | None:
        """Prefill ``req`` into slot ``r``; returns a Completion iff the
        very first sampled token already finishes the request."""
        last = self._prefill_into(r, req.prompt)
        return self._start_slot(r, req, len(req.prompt), last)

    def _admit_resume(self, req: Request) -> Completion | None:
        """Continue a parked session in ITS OWN slot (consuming the
        parked entry)."""
        r, pos, last_tok = self._parked.pop(req.session)
        self._parked_slots.discard(r)
        self.stats["resumes"] += 1
        return self._continue_into(r, r, pos, last_tok, req)

    def _admit_fork(self, r_target: int, req: Request) -> Completion | None:
        """FORK a parked template (shared prefix) into a free slot: the
        template row is read, not consumed — it keeps serving forks."""
        r_src, pos, last_tok = self._parked[req.prefix]
        # Refresh the template's LRU position (dict insertion order IS the
        # eviction order): without the re-insert a hot, frequently-forked
        # template stays oldest and dies before stale idle sessions.
        del self._parked[req.prefix]
        self._parked[req.prefix] = (r_src, pos, last_tok)
        self.stats["forks"] += 1
        return self._continue_into(r_src, r_target, pos, last_tok, req)

    def _continue_into(self, r_src: int, r_target: int, pos: int,
                      last_tok: int | None,
                      req: Request) -> Completion | None:
        """Shared continuation: extract row ``r_src``, pin its free-ran
        counters back to ``pos``, ingest [last unconsumed token +] the
        new turn in one bucketed multi-token continuation, scatter into
        ``r_target``."""
        turn = ([] if last_tok is None else [last_tok]) + req.prompt
        T = len(turn)
        Tb = self._bucket(T)
        if pos + Tb > self.max_seq_len:
            # exact-fit tail pad instead of the power-of-two bucket: the
            # vmap'd dynamic_update_slice would CLAMP an overhanging
            # write, shifting real tokens. (Rare — only near context end;
            # costs one extra compile per distinct tail length.)
            Tb = self.max_seq_len - pos
        ids = np.zeros((1, Tb), np.int32)
        ids[0, :T] = turn
        row = self._extract_row(r_src, pos)
        # _prefill_step doubles as the continuation executable: the
        # static model arg (decode_multi twin) keys a separate compile
        # that appends at the row's offset instead of position 0.
        last, row = _prefill_step(
            self._row_model_multi, self.params, row, jnp.asarray(ids),
            jnp.asarray([T], jnp.int32))
        self._install_row_range(r_target, row, pos, T)
        return self._start_slot(r_target, req, pos + T, last)

    def _post_admission_state(self, r: int, req: Request) -> None:
        """Subclass hook: runs after _set_row_sampling_state, before the
        admission sample (see _start_slot). Base: nothing."""

    def _can_admit(self, req: Request) -> bool:
        """Subclass hook: may the scheduler admit ``req`` right now
        beyond slot availability? Base: always (slots are the only
        dense capacity). Returning False leaves the request queued."""
        del req
        return True

    def _set_row_sampling_state(self, r: int, req: Request) -> None:
        """ONE place that loads a slot's per-request sampling state
        (penalties + logit bias) — shared by the causal admission tail
        and the seq2seq _admit override."""
        self._rep[r] = req.repetition_penalty
        self._pres[r] = req.presence_penalty
        self._freq[r] = req.frequency_penalty
        self._top_p[r] = self.top_p if req.top_p is None else req.top_p
        self._min_p[r] = self.min_p if req.min_p is None else req.min_p
        self._seed[r] = -1 if req.seed is None else req.seed
        self._ntok_base[r] = 0
        self._counts[r] = 0.0
        self._gen_counts[r] = 0.0
        self._bias[r] = 0.0
        self._has_bias[r] = bool(req.logit_bias)
        if req.logit_bias:
            for k, v in req.logit_bias.items():
                self._bias[r, int(k)] = float(v)

    def _start_slot(self, r: int, req: Request, pos: int,
                    last_logits) -> Completion | None:
        """Shared admission tail: sample the first token and activate the
        slot; returns a Completion iff that token already finishes."""
        self.rng, step_rng = jax.random.split(self.rng)
        self._set_row_sampling_state(r, req)
        # hook for subclass admission state that must land BEFORE the
        # first-token sampling (paged preemption: seeded-chain offset +
        # generated-count restoration for requeued requests)
        self._post_admission_state(r, req)
        penalized = (req.repetition_penalty != 1.0
                     or req.presence_penalty != 0.0
                     or req.frequency_penalty != 0.0
                     or bool(req.logit_bias))
        if penalized:
            if self._count_prompt:
                # Causal LMs: the prompt joins the REPETITION context
                # (_counts) only — the OpenAI additive penalties score
                # generated tokens (_gen_counts, empty at admission).
                # Seq2seq overrides this off — its "prompt" is the
                # ENCODER source (HF applies repetition_penalty to
                # decoder ids the same way); its first token still
                # routes through the penalized sampler so logit_bias
                # applies from token one.
                np.add.at(self._counts[r],
                          np.asarray(req.prompt, np.int64), 1.0)
            tok, lp = _sample_rows_penalized(
                last_logits, step_rng,
                jnp.asarray([req.temperature], jnp.float32),
                jnp.asarray(self._counts[r:r + 1]),
                jnp.asarray(self._gen_counts[r:r + 1]),
                jnp.asarray([req.repetition_penalty], jnp.float32),
                jnp.asarray([req.presence_penalty], jnp.float32),
                jnp.asarray([req.frequency_penalty], jnp.float32),
                (jnp.asarray(self._bias[r:r + 1]) if req.logit_bias
                 else jnp.float32(0.0)),
                jnp.asarray(self._top_p[r:r + 1]),
                jnp.asarray(self._min_p[r:r + 1]),
                jnp.asarray(self._seed[r:r + 1]),
                # nothing generated THIS admission; requeued requests
                # carry their pre-preemption draw count in _ntok_base
                jnp.asarray(self._ntok_base[r:r + 1], jnp.int32),
                self.top_k)
        else:
            tok, lp = _sample_rows(
                last_logits, step_rng,
                jnp.asarray([req.temperature], jnp.float32),
                jnp.asarray(self._top_p[r:r + 1]),
                jnp.asarray(self._min_p[r:r + 1]),
                jnp.asarray(self._seed[r:r + 1]),
                jnp.asarray(self._ntok_base[r:r + 1], jnp.int32),
                self.top_k)
        first = int(tok[0])
        if penalized:
            self._counts[r, first] += 1.0
            self._gen_counts[r, first] += 1.0
        self.stats["generated_tokens"] += 1
        self._req[r] = req
        self._generated[r] = [first]
        self._logprobs[r] = [float(lp[0])]
        self._pending[r] = first
        self._temp[r] = req.temperature
        self._pos[r] = pos
        if self.spec_k:
            # proposal context = THIS request's prompt + generated
            # (resumed sessions' earlier turns live only as KV — same
            # scope as the penalty context)
            self._ctx[r] = list(req.prompt) + [first]
            self._ngram_idx[r] = _ngram_build(self._ctx[r],
                                              self.spec_ngram)
        return self._maybe_finish(r, first)

    def _maybe_finish(self, r: int, token: int) -> Completion | None:
        req = self._req[r]
        done_eos = req.eos_id is not None and token == req.eos_id
        done_len = len(self._generated[r]) >= req.max_new_tokens
        if not (done_eos or done_len):
            return None
        self._req[r] = None  # slot free; cache row is dead until re-admit
        # Reset penalty settings with the slot: a stale rep != 1 on a free
        # row would keep routing EVERY step through the penalized sampler
        # (and its counts transfer) long after the request finished.
        self._rep[r], self._pres[r], self._freq[r] = 1.0, 0.0, 0.0
        self._top_p[r], self._min_p[r] = self.top_p, self.min_p
        self._seed[r] = -1
        # Row cleared WITH the flag: a stale row would still ship (wrong)
        # whenever some other row keeps the penalized path engaged.
        self._bias[r] = 0.0
        self._has_bias[r] = False
        session = None
        if req.keep:
            # Park: the conversation's K/V stays resident. The LAST
            # sampled token was never fed back (its K/V is not in the
            # cache), so it rides in the parked tuple and is prepended to
            # the next turn at resume.
            session = req.uid
            self._parked[session] = (r, int(self._pos[r]),
                                     self._generated[r][-1])
            self._parked_slots.add(r)
        return Completion(req.uid, req.prompt, self._generated[r],
                          "eos" if done_eos else "length", session=session,
                          logprobs=self._logprobs[r])

    def _evict_lru_parked(self, force: bool = False) -> int | None:
        """Free the oldest parked slot not referenced by a queued
        resume/fork; its session dies (a later submit(session=) raises).
        Returns the freed slot, or None if every parked session has a
        pending continuation. ``force`` drops the protection — the
        DEADLOCK breaker for when nothing is active and every slot is a
        protected template (e.g. slots=1 with a queued fork of the only
        template: the fork needs a second slot that can never appear);
        the sacrificed session's queued continuations then surface as
        session_evicted completions instead of hanging forever."""
        queued = {q.session for q in self.queue if q.session is not None}
        queued |= {q.prefix for q in self.queue if q.prefix is not None}
        # _evict_protect: sids that must survive even a forced eviction —
        # a fork ALREADY POPPED from the queue is mid-admission against
        # its template (the queued-set above no longer sees it); evicting
        # that template under block/slot pressure would corrupt the
        # copy-on-write source mid-share (paged) or KeyError the
        # scheduler (dense).
        for sid in list(self._parked):  # insertion order == LRU
            if sid in self._evict_protect:
                continue
            if force or sid not in queued:
                r, _, _ = self._parked.pop(sid)
                self._parked_slots.discard(r)
                self._template_tokens.pop(sid, None)
                return r
        return None

    def can_preload(self, prompt_len: int | None = None) -> bool:
        """Pure capacity check: would preload() find a slot right now?
        True when a slot is free, or some parked entry is evictable
        (not referenced by a queued continuation). No side effects —
        callers use it to fall back instead of catching preload's
        RuntimeError (which would also swallow device errors).
        ``prompt_len`` (the template's token count) lets capacity-
        constrained subclasses (paged) also check block availability;
        the dense batcher's slots are full-length rows, so it is
        ignored here."""
        del prompt_len
        for r in range(self.slots):
            if self._req[r] is None and r not in self._parked_slots:
                return True
        queued = {q.session for q in self.queue if q.session is not None}
        queued |= {q.prefix for q in self.queue if q.prefix is not None}
        return any(sid not in queued for sid in self._parked)

    def release(self, sid: int) -> bool:
        """Explicitly drop a parked session/template (frees its slot now
        instead of waiting for LRU pressure). Queued continuations of it
        will surface as session_evicted."""
        entry = self._parked.pop(sid, None)
        if entry is None:
            return False
        self._parked_slots.discard(entry[0])
        self._template_tokens.pop(sid, None)
        return True

    def cancel(self, uid: int) -> bool:
        """Stop a request: de-queue it, or free its active slot (the row
        is dead until re-admitted, like any finished slot). Parked
        sessions are untouched — canceling a queued resume leaves its
        session parked. Returns whether anything was canceled; a
        canceled request yields NO Completion."""
        for i, q in enumerate(self.queue):
            if q.uid == uid:
                del self.queue[i]
                return True
        for r in range(self.slots):
            if self._req[r] is not None and self._req[r].uid == uid:
                self._req[r] = None
                # Same reset _maybe_finish performs: a stale rep != 1 on
                # the freed row would route every later step through the
                # penalized sampler (and its counts transfer).
                self._rep[r], self._pres[r], self._freq[r] = 1.0, 0.0, 0.0
                self._top_p[r], self._min_p[r] = self.top_p, self.min_p
                self._seed[r] = -1
                self._bias[r] = 0.0
                self._has_bias[r] = False
                return True
        return False

    def new_tokens_since(self, seen: dict[int, int]) -> dict[int, list[int]]:
        """uid -> ids generated beyond seen[uid], for every ACTIVE slot
        whose uid appears in ``seen``. The supported tap for streaming
        consumers (tools/serve_http.py) — callers never touch slot state.
        Tokens of requests that just FINISHED are not here; read them from
        the step()/run() Completion."""
        out: dict[int, list[int]] = {}
        for r in self.active_slots:
            uid = self._req[r].uid
            n = seen.get(uid)
            if n is not None and len(self._generated[r]) > n:
                out[uid] = self._generated[r][n:]
        return out

    def _decode(self, ids):
        """One batched decode step over all slots; returns (B, V) logits."""
        logits, self.cache = _decode_step(
            self.model, self.params, self.cache, ids)
        return logits

    def _decode_multi(self, ids):
        """Batched multi-token step returning ALL positions' logits —
        the speculative verify forward."""
        logits, self.cache = _decode_multi_logits(
            self._model_multi, self.params, self.cache, ids)
        return logits

    @property
    def active_slots(self) -> list[int]:
        return [r for r in range(self.slots) if self._req[r] is not None]

    def active_uids(self) -> list[int]:
        """uids currently holding a slot — the serving reliability
        plane's leak sweep compares these against its live waiters
        (serving_plane/; a slot whose waiter died must be reclaimed,
        never squat until LRU pressure)."""
        return [self._req[r].uid for r in self.active_slots]

    def slot_accounting(self) -> dict:
        """Slot/KV occupancy snapshot for /healthz and the slot-leak
        tests: every slot is exactly one of active / parked / free, and
        the queue depth rides along (the admission controller's primary
        signal). Paged batchers add their block-pool occupancy."""
        active = len(self.active_slots)
        parked = len(self._parked_slots)
        out = {"slots": self.slots, "active": active, "parked": parked,
               "free": self.slots - active - parked,
               "queued": len(self.queue)}
        if hasattr(self, "blocks_in_use"):
            out["blocks_in_use"] = int(self.blocks_in_use())
        return out

    def _free_slot(self) -> int | None:
        for r in range(self.slots):
            if self._req[r] is None and r not in self._parked_slots:
                return r
        return self._evict_lru_parked()

    def step(self) -> list[Completion]:
        """One scheduler quantum: admit ALL queued session resumes (their
        slots are reserved — a capacity-blocked fresh request at the
        queue head must not starve them into a livelock), then fresh
        requests into free slots (evicting the LRU parked session under
        pressure), then one batched decode step advancing every active
        slot by one token."""
        t_admit = time.perf_counter()
        finished: list[Completion] = []
        fresh: deque[Request] = deque()
        while self.queue:
            req = self.queue.popleft()
            if req.session is None:
                fresh.append(req)
                continue
            if req.session not in self._parked:
                # evicted between submit and admission (extreme slot
                # pressure): surface as a failed completion rather
                # than raising inside the scheduler
                finished.append(Completion(
                    req.uid, req.prompt, [], "session_evicted"))
                continue
            done = self._admit_resume(req)
            if done is not None:
                finished.append(done)
        self.queue = fresh
        while self.queue:
            req = self.queue[0]
            if req.prefix is not None and req.prefix not in self._parked:
                # template evicted between submit and admission
                self.queue.popleft()
                finished.append(Completion(
                    req.uid, req.prompt, [], "session_evicted"))
                continue
            if not self._can_admit(req):
                # subclass capacity gate (paged: block budget). Checked
                # BEFORE the slot search: _free_slot may LRU-evict a
                # parked session to produce a slot, and destroying a
                # live session for an admission that then fails the
                # gate would be a pure loss.
                break
            r = self._free_slot()
            if r is None and not self.active_slots:
                # nothing is decoding, so no slot will EVER drain:
                # sacrifice a protected template rather than deadlock
                r = self._evict_lru_parked(force=True)
            if r is None:
                break  # every slot active or resume-reserved
            self.queue.popleft()
            if req.prefix is not None and req.prefix not in self._parked:
                # the force-eviction above sacrificed THIS fork's own
                # template (slots=1 case: the fork could never get a
                # second slot anyway) — surface, don't KeyError
                finished.append(Completion(
                    req.uid, req.prompt, [], "session_evicted"))
                continue
            done = (self._admit_fork(r, req) if req.prefix is not None
                    else self._admit(r, req))
            if done is not None:
                finished.append(done)
        active = self.active_slots
        self.stats["admit_ms"] += (time.perf_counter() - t_admit) * 1e3
        if not active:
            return finished
        if self.spec_k:
            return finished + self._spec_step(active)
        # Rows needing >=1 more token feed their pending sampled token;
        # free rows feed token 0 and are ignored (their cache_index
        # free-runs — reset at the next admit, clamped writes stay in the
        # dead row).
        t_dev = time.perf_counter()
        logits = self._decode(jnp.asarray(self._pending)[:, None])
        self.rng, step_rng = jax.random.split(self.rng)
        # seeded rows' key chain advances by GENERATED count + any
        # pre-preemption base (inactive rows' stale counts are harmless
        # — their draws are discarded)
        ntok = jnp.asarray(
            self._ntok_base + np.asarray(
                [len(g) for g in self._generated], np.int32), jnp.int32)
        any_penalized = (np.any(self._rep != 1.0)
                         or np.any(self._pres != 0.0)
                         or np.any(self._freq != 0.0)
                         or np.any(self._has_bias))
        if any_penalized:
            # Penalty-free rows carry (rep=1, pres=0, freq=0) → identity,
            # so one batched penalized step serves the mixed case; the
            # counts transfer happens only on these steps.
            nxt_dev, lp_dev = _sample_rows_penalized(
                logits, step_rng, jnp.asarray(self._temp),
                jnp.asarray(self._counts), jnp.asarray(self._gen_counts),
                jnp.asarray(self._rep),
                jnp.asarray(self._pres), jnp.asarray(self._freq),
                # No biased row → ship a broadcastable scalar zero, not
                # the (slots, V) zero matrix (its own compiled variant;
                # two shapes total, both stable).
                (jnp.asarray(self._bias) if self._has_bias.any()
                 else jnp.float32(0.0)),
                jnp.asarray(self._top_p), jnp.asarray(self._min_p),
                jnp.asarray(self._seed), ntok,
                self.top_k)
        else:
            nxt_dev, lp_dev = _sample_rows(
                logits, step_rng, jnp.asarray(self._temp),
                jnp.asarray(self._top_p), jnp.asarray(self._min_p),
                jnp.asarray(self._seed), ntok,
                self.top_k)
        nxt, lps = np.asarray(nxt_dev), np.asarray(lp_dev)
        t_host = time.perf_counter()
        self.stats["device_ms"] += (t_host - t_dev) * 1e3
        self.stats["steps"] += 1
        self.stats["slot_token_slots"] += self.slots
        for r in active:
            if self._req[r] is None:
                continue  # preempted mid-step (paged block pressure)
            tok = int(nxt[r])
            self._generated[r].append(tok)
            self._logprobs[r].append(float(lps[r]))
            if any_penalized:
                self._counts[r, tok] += 1.0
                self._gen_counts[r, tok] += 1.0
            self._pending[r] = tok
            self._pos[r] += 1  # the fed token's K/V is now in the cache
            self.stats["generated_tokens"] += 1
            done = self._maybe_finish(r, tok)
            if done is not None:
                finished.append(done)
        self.stats["host_ms"] += (time.perf_counter() - t_host) * 1e3
        return finished

    def _spec_step(self, active: list[int]) -> list[Completion]:
        """One prompt-lookup speculative round over all slots: per-row
        n-gram proposals from the incremental index (O(1) per row, not
        an O(context) rescan), ONE (slots, k+1) verify forward, per-row
        acceptance and cache rollback. Commits 1..k+1 tokens per active
        row; output law identical to the plain path (point-mass accept),
        including penalized/biased rows (the penalized kernel advances
        the count context per accepted draft)."""
        k = self.spec_k
        finished: list[Completion] = []
        t_prop = time.perf_counter()
        props = np.zeros((self.slots, k), np.int32)
        for r in active:
            p = _ngram_propose(self._ctx[r], self._ngram_idx[r],
                               self.spec_ngram, k)
            # no match → a known-reject proposal: the round degrades to
            # exactly one committed token, a plain step's outcome
            props[r] = p if p is not None else [int(self._pending[r])] * k
        t_dev = time.perf_counter()
        self.stats["host_ms"] += (t_dev - t_prop) * 1e3
        ids = np.concatenate([self._pending[:, None], props], axis=1)
        logits = self._decode_multi(jnp.asarray(ids))
        self.rng, step_rng = jax.random.split(self.rng)
        ntok = jnp.asarray(
            self._ntok_base + np.asarray(
                [len(g) for g in self._generated], np.int32), jnp.int32)
        any_penalized = (np.any(self._rep != 1.0)
                         or np.any(self._pres != 0.0)
                         or np.any(self._freq != 0.0)
                         or np.any(self._has_bias))
        if any_penalized:
            # Penalty-free rows carry identity settings, so one batched
            # penalized verify serves the mixed case (same routing rule
            # as the plain step).
            n_dev, nxt_dev, dlp_dev, nlp_dev = _spec_verify_rows_penalized(
                logits, step_rng, jnp.asarray(self._temp),
                jnp.asarray(props), jnp.asarray(self._counts),
                jnp.asarray(self._gen_counts), jnp.asarray(self._rep),
                jnp.asarray(self._pres), jnp.asarray(self._freq),
                (jnp.asarray(self._bias) if self._has_bias.any()
                 else jnp.float32(0.0)),
                jnp.asarray(self._top_p), jnp.asarray(self._min_p),
                jnp.asarray(self._seed), ntok, self.top_k)
        else:
            n_dev, nxt_dev, dlp_dev, nlp_dev = _spec_verify_rows(
                logits, step_rng, jnp.asarray(self._temp),
                jnp.asarray(props), jnp.asarray(self._top_p),
                jnp.asarray(self._min_p), jnp.asarray(self._seed), ntok,
                self.top_k)
        n_acc = np.asarray(n_dev)
        nxt = np.asarray(nxt_dev)
        d_lp = np.asarray(dlp_dev)
        n_lp = np.asarray(nlp_dev)
        t_host = time.perf_counter()
        self.stats["device_ms"] += (t_host - t_dev) * 1e3
        self.stats["steps"] += 1
        self.stats["slot_token_slots"] += self.slots * (k + 1)
        self.stats["spec_rounds"] = self.stats.get("spec_rounds", 0) \
            + len(active)
        for r in active:
            if self._req[r] is None:
                continue  # preempted mid-step (paged block pressure)
            n_r = int(n_acc[r])
            self.stats["spec_accepted"] = self.stats.get(
                "spec_accepted", 0) + n_r
            committed = [int(props[r, i]) for i in range(n_r)] \
                + [int(nxt[r])]
            lps = [float(d_lp[r, i]) for i in range(n_r)] \
                + [float(n_lp[r])]
            base = int(self._pos[r])
            done = None
            for i, (tok, lp) in enumerate(zip(committed, lps)):
                self._generated[r].append(tok)
                self._logprobs[r].append(lp)
                _ngram_append(self._ctx[r], self._ngram_idx[r], tok,
                              self.spec_ngram)
                if any_penalized:
                    # mirror of the kernel's cumulative count advance —
                    # committed tokens join both penalty contexts
                    self._counts[r, tok] += 1.0
                    self._gen_counts[r, tok] += 1.0
                # ingested = pending + accepted d_1..d_i (the token being
                # committed is the NOT-ingested rider — same invariant as
                # the plain step, so _maybe_finish's parking math holds)
                self._pos[r] = base + 1 + i
                self._pending[r] = tok
                self.stats["generated_tokens"] += 1
                done = self._maybe_finish(r, tok)
                if done is not None:
                    finished.append(done)
                    break
        # rewind every row's counters: the verify advanced them by k+1;
        # live rows resume at pending + accepted, other rows don't care
        # (dead rows reset at admit, parked rows re-pin at resume)
        self.cache = _set_row_indices(
            self.cache, jnp.asarray(self._pos, jnp.int32))
        self.stats["host_ms"] += (time.perf_counter() - t_host) * 1e3
        return finished

    def run(self):
        """Drive step() until queue and slots drain, yielding Completions
        as they finish (arrival-order-independent)."""
        while self.queue or self.active_slots:
            yield from self.step()


# ------------------------------------------------------ paged KV serving

@partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def _paged_decode_step(model, params, cache, ids, tables):
    """The batched decode step over a paged pool — identical contract to
    generate._decode_step plus the host block tables."""
    from pytorch_distributed_train_tpu import quant

    params = quant.dequantize_tree(params, model.dtype)
    logits, updated = model.apply(
        {"params": params, "cache": cache}, ids, train=False,
        mutable=["cache"], block_tables=tables,
    )
    return logits[:, -1], updated["cache"]


@partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def _paged_decode_multi(model, params, cache, ids, tables):
    """Paged twin of _decode_multi_logits (speculative verify)."""
    from pytorch_distributed_train_tpu import quant

    params = quant.dequantize_tree(params, model.dtype)
    logits, updated = model.apply(
        {"params": params, "cache": cache}, ids, train=False,
        mutable=["cache"], block_tables=tables,
    )
    return logits, updated["cache"]


@partial(jax.jit, donate_argnums=(1,))
def _paged_gather_row(paged_cache, dense_zero, phys):
    """One slot's logical K/V view gathered out of the pools into a
    dense B=1 row cache (``phys``: (L,) physical token indices, OOB
    where unallocated — those positions read zero and stay masked).
    The inverse of _paged_scatter_row; pairs pool_key<->cached_key
    leaves by path."""
    from flax import traverse_util

    pf = traverse_util.flatten_dict(paged_cache, sep="/")
    df = traverse_util.flatten_dict(dense_zero, sep="/")
    out = {}
    for path, leaf in df.items():
        name = path.rsplit("/", 1)[-1]
        if name in ("cached_key", "cached_value"):
            pool = pf[path.replace("cached_", "pool_")]
            L = leaf.shape[1]
            out[path] = jnp.take(
                pool, phys[:L], axis=0, mode="fill",
                fill_value=0)[None].astype(leaf.dtype)
        else:
            out[path] = leaf  # index counters: caller pins them
    return traverse_util.unflatten_dict(out, sep="/")


@partial(jax.jit, donate_argnums=(0,))
def _paged_scatter_row(paged_cache, row_cache, phys, r, new_index):
    """Land a dense B=1 row cache in slot ``r`` of the paged pools: the
    FULL logical row scatters through ``phys`` (writes to unallocated /
    sentinel positions drop — one executable regardless of how much of
    the row is real), and slot r's cache_index pins to ``new_index``.
    Writing the whole row is correct even over fork-shared blocks: a
    shared block's region was gathered unmodified from those very
    blocks, so the write-back is value-identical; only the new range
    differs, and it lands in owned blocks by the sharing rule (forks
    never share the block containing the fork point — it is copied)."""
    from flax import traverse_util

    pf = traverse_util.flatten_dict(paged_cache, sep="/")
    rf = traverse_util.flatten_dict(row_cache, sep="/")
    out = {}
    for path, leaf in pf.items():
        name = path.rsplit("/", 1)[-1]
        if name in ("pool_key", "pool_value"):
            row = rf[path.replace("pool_", "cached_")]  # (1, L, H, D)
            L = row.shape[1]
            out[path] = leaf.at[phys[:L]].set(
                row[0].astype(leaf.dtype), mode="drop")
        elif name == "cache_index":
            out[path] = leaf.at[r].set(new_index.astype(leaf.dtype))
        else:
            out[path] = leaf
    return traverse_util.unflatten_dict(out, sep="/")


@partial(jax.jit, static_argnums=(3,), donate_argnums=(0,))
def _paged_copy_block(paged_cache, src, dst, bs: int):
    """Copy physical block ``src`` -> ``dst`` in every layer's pools —
    the copy-on-write step for a fork whose prefix ends mid-block."""
    from flax import traverse_util

    pf = traverse_util.flatten_dict(paged_cache, sep="/")
    out = {}
    for path, leaf in pf.items():
        if path.rsplit("/", 1)[-1] in ("pool_key", "pool_value"):
            blk = jax.lax.dynamic_slice_in_dim(leaf, src * bs, bs, 0)
            out[path] = jax.lax.dynamic_update_slice_in_dim(
                leaf, blk, dst * bs, 0)
        else:
            out[path] = leaf
    return traverse_util.unflatten_dict(out, sep="/")


class PagedContinuousBatcher(ContinuousBatcher):
    """Continuous batching over a PAGED KV cache — the vLLM
    PagedAttention role, TPU-shaped (SURVEY §7.4.5's static-shape
    discipline kept: every executable still has static shapes; paging
    changes WHERE rows live, not the shapes the compiler sees).

    The dense batcher reserves one (slots, max_seq_len, H_kv, D) row
    per slot per layer — every slot pays worst-case length in HBM. Here
    K/V live in a flat pool of ``page_blocks`` blocks of ``page_size``
    tokens; each slot maps logical block j -> physical block through a
    host-managed table, so RESIDENT KV scales with actual sequence
    lengths: on a 16 GB chip that is the serving capacity currency.
    Blocks are refcounted — prefix forks (templates, sessions) share
    full blocks copy-on-write (the block containing the fork point is
    copied; the rest alias), so one preloaded system prompt costs its
    own blocks once no matter how many requests fork it.

    Out-of-bounds semantics do the policing, not branches: unallocated
    table entries hold the sentinel ``page_blocks``, so a dead row's
    free-running writes and a parked row's speculative-margin writes
    land out of bounds and DROP (scatter mode='drop'), and gathers from
    unallocated blocks read zero (mode='fill') behind the position mask
    — the paged analogue of the dense batcher's masked-garbage-row
    discipline.

    Scheduling: blocks allocate on demand (admission takes the prompt's
    blocks; each decode step takes at most one more per active row).
    On exhaustion the LRU unreferenced parked session is evicted; if
    nothing is evictable the step raises RuntimeError — there is no
    vLLM-style preempt-and-recompute yet (size ``page_blocks`` for the
    workload; ``submit`` rejects any single request that could not fit
    the pool even alone). v1 scope: llama-family models, single chip
    (``mesh`` unsupported — shard the pool's head axis over 'tensor'
    the way _cache_shardings does for dense rows when it lands).
    """

    def __init__(self, model_cfg: ModelConfig, precision: PrecisionConfig,
                 params: Any, *, slots: int = 4, page_size: int = 16,
                 page_blocks: int = 0, top_k: int = 0, top_p: float = 0.0,
                 min_p: float = 0.0, rng=None, min_bucket: int = 16,
                 auto_prefix_min: int = 0, spec_k: int = 0,
                 spec_ngram: int = 3):
        if not model_cfg.name.startswith("llama"):
            raise ValueError(
                f"paged serving covers the llama family (per-row rope "
                f"offsets, no learned-position counters), got "
                f"{model_cfg.name!r}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self._page = page_size
        self._mb = -(-model_cfg.max_seq_len // page_size)
        # default pool = dense-equivalent capacity (the win then comes
        # from raising slots, not shrinking the pool)
        self._nblk = page_blocks or slots * self._mb
        super().__init__(model_cfg, precision, params, slots=slots,
                         top_k=top_k, top_p=top_p, min_p=min_p, rng=rng,
                         min_bucket=min_bucket,
                         auto_prefix_min=auto_prefix_min,
                         spec_k=spec_k, spec_ngram=spec_ngram)
        self._dense_model = build_serving_model(model_cfg, precision)
        self._dense_multi = dataclasses.replace(self._dense_model,
                                                decode_multi=True)
        # host allocator: free stack + per-block refcounts + per-slot
        # block tables (sentinel self._nblk = unallocated)
        self._free_list = list(range(self._nblk))[::-1]
        self._refcnt = np.zeros(self._nblk, np.int64)
        self._tables = np.full((slots, self._mb), self._nblk, np.int32)
        self._nalloc = np.zeros(slots, np.int64)
        # preempt-and-recompute bookkeeping: uid -> stash of the
        # pre-preemption state (original prompt, committed tokens +
        # logprobs, seeded-chain offset) for completion stitching and
        # exact seeded resumption
        self._preempted: dict[int, dict] = {}
        self.stats["preemptions"] = 0

    # ------------------------------------------------------ model hooks
    def _build_batched_model(self, model_cfg, precision):
        m = build_serving_model(model_cfg, precision)
        return dataclasses.replace(m, paged=True, page_size=self._page,
                                   paged_blocks=self._nblk)

    @property
    def _row_model(self):
        return self._dense_model

    @property
    def _row_model_multi(self):
        return self._dense_multi

    def _alloc_row_cache(self):
        return init_cache(self._dense_model, 1)

    # -------------------------------------------------- block allocator
    def blocks_in_use(self) -> int:
        return self._nblk - len(self._free_list)

    def _blocks_needed(self, pos_end: int) -> int:
        return -(-pos_end // self._page)

    def _ensure_blocks(self, r: int, pos_end: int) -> None:
        """Grow slot ``r``'s table to cover logical positions
        [0, pos_end), reclaiming under pressure in escalation order:
        evict LRU parked sessions, then PREEMPT the youngest plain
        active request (free its blocks, requeue it for re-prefill —
        the vLLM recompute policy; greedy and seeded-sampled outputs
        are bit-identical to the uninterrupted run, unseeded sampled
        rows redraw from the same law), and only then raise.
        Capped at the table width: a speculative round straddling the
        context end asks for pos + k + 1 > max_seq_len, whose excess
        writes the in-kernel flat clamp already piles on Lp-1 — they
        need no blocks (and the table has no column for them)."""
        need = min(self._blocks_needed(pos_end), self._mb)
        while int(self._nalloc[r]) < need:
            # evicting a fork-shared template may free zero blocks
            # (refcounts stay > 0) — keep evicting until one frees
            while not self._free_list:
                if self._evict_lru_parked() is not None:
                    continue
                v = self._preempt_victim(exclude=r)
                if v is None:
                    raise RuntimeError(
                        f"KV block pool exhausted ({self._nblk} blocks "
                        f"of {self._page} tokens, all in use, no "
                        "parked session evictable and no plain active "
                        "request preemptible) — raise page_blocks "
                        "or lower concurrency")
                self._preempt_slot(v)
            b = self._free_list.pop()
            self._tables[r, int(self._nalloc[r])] = b
            self._refcnt[b] = 1
            self._nalloc[r] += 1

    def _free_slot_blocks(self, r: int) -> None:
        for j in range(int(self._nalloc[r])):
            b = int(self._tables[r, j])
            self._refcnt[b] -= 1
            if self._refcnt[b] == 0:
                self._free_list.append(b)
        self._tables[r, :] = self._nblk
        self._nalloc[r] = 0

    def _share_blocks(self, src: int, dst: int, pos: int) -> None:
        """Fork-time aliasing: dst shares src's FULL blocks below
        ``pos`` (refcount++); the block containing ``pos`` (if partial)
        is copied — the only block a fork can ever write below its new
        range."""
        self._free_slot_blocks(dst)
        full = pos // self._page
        for j in range(full):
            b = int(self._tables[src, j])
            self._tables[dst, j] = b
            self._refcnt[b] += 1
        self._nalloc[dst] = full
        if pos % self._page:
            self._ensure_blocks(dst, pos)  # exactly one fresh block
            self.cache = _paged_copy_block(
                self.cache, jnp.int32(int(self._tables[src, full])),
                jnp.int32(int(self._tables[dst, full])), self._page)

    def _preempt_victim(self, exclude: int) -> int | None:
        """The youngest (latest-admitted, LIFO — least work lost) plain
        active slot. keep/session/prefix requests are never victims:
        their context lives partly in resident KV (earlier turns, a
        shared template) and cannot be reconstructed from the request
        alone."""
        best, best_uid = None, -1
        for s in self.active_slots:
            req = self._req[s]
            if s == exclude or req.keep or req.session is not None \
                    or req.prefix is not None:
                continue
            if req.uid > best_uid:
                best, best_uid = s, req.uid
        return best

    def _preempt_slot(self, v: int) -> None:
        """Free slot ``v``'s blocks and requeue its request for
        re-prefill: the requeued prompt is original prompt + committed
        tokens MINUS the pending one (whose K/V was never ingested) —
        re-admission's first sample then re-derives the pending token
        (identical under greedy and seeded rows via the _ntok_base
        chain offset; unseeded sampled rows redraw from the same law).
        Committed tokens/logprobs stash per-uid for completion
        stitching; repeated preemption of the same request
        accumulates."""
        req = self._req[v]
        gen = self._generated[v]
        lps = self._logprobs[v]
        stash = self._preempted.get(req.uid)
        if stash is None:
            stash = {"prompt": req.prompt, "tokens": [], "logprobs": [],
                     "ntok_base": 0}
            self._preempted[req.uid] = stash
        # committed = everything but the pending rider (gen[-1]); its
        # draw is re-made at re-admission (chain position preserved)
        stash["tokens"] += gen[:-1]
        stash["logprobs"] += lps[:-1]
        stash["ntok_base"] += len(gen) - 1
        requeued = dataclasses.replace(
            req,
            prompt=list(req.prompt) + gen[:-1],
            max_new_tokens=req.max_new_tokens - (len(gen) - 1))
        self._req[v] = None
        self._rep[v], self._pres[v], self._freq[v] = 1.0, 0.0, 0.0
        self._top_p[v], self._min_p[v] = self.top_p, self.min_p
        self._seed[v] = -1
        self._ntok_base[v] = 0
        self._bias[v] = 0.0
        self._has_bias[v] = False
        self._free_slot_blocks(v)
        self.queue.appendleft(requeued)
        self.stats["preemptions"] += 1

    def _post_admission_state(self, r: int, req: Request) -> None:
        stash = self._preempted.get(req.uid)
        if stash is None:
            return
        # resume the seeded fold_in chain where the preempted run left
        # off, and restore the GENERATED-only penalty context: the
        # stashed tokens ride inside req.prompt (so _counts — the
        # repetition context — already has them) but OpenAI presence/
        # frequency must keep scoring them as generated output
        self._ntok_base[r] = stash["ntok_base"]
        if stash["tokens"] and (req.presence_penalty != 0.0
                                or req.frequency_penalty != 0.0):
            np.add.at(self._gen_counts[r],
                      np.asarray(stash["tokens"], np.int64), 1.0)

    def _phys_row(self, r: int) -> np.ndarray:
        """(max_seq_len,) physical token indices of slot ``r`` (OOB
        sentinel where unallocated)."""
        j = np.arange(self.max_seq_len)
        pb = self._tables[r, j // self._page].astype(np.int64)
        return (pb * self._page + j % self._page).astype(np.int32)

    # ------------------------------------------------------- row hooks
    def _install_row(self, r: int, row_cache, true_len: int) -> None:
        self._free_slot_blocks(r)  # idempotent; covers any stale state
        self._ensure_blocks(r, true_len)
        self.cache = _paged_scatter_row(
            self.cache, row_cache, jnp.asarray(self._phys_row(r)),
            jnp.int32(r), jnp.int32(true_len))

    def _extract_row(self, r: int, pos: int):
        row = _paged_gather_row(self.cache, self._alloc_row_cache(),
                                jnp.asarray(self._phys_row(r)))
        return _set_row_index(row, jnp.int32(pos))

    def _install_row_range(self, r: int, row_cache, pos: int,
                           T: int) -> None:
        self._ensure_blocks(r, pos + T)
        self.cache = _paged_scatter_row(
            self.cache, row_cache, jnp.asarray(self._phys_row(r)),
            jnp.int32(r), jnp.int32(pos + T))

    # ------------------------------------------------- lifecycle frees
    def _admit_fork(self, r_target: int, req: Request):
        # Shield the source template for the whole admission: the fork
        # was already popped from the queue, so the LRU evictor's
        # queued-protection no longer covers it — block pressure during
        # _share_blocks/_ensure_blocks could otherwise evict and
        # sentinel the very blocks being shared/copied.
        r_src, pos, _ = self._parked[req.prefix]
        self._evict_protect.add(req.prefix)
        try:
            self._share_blocks(r_src, r_target, pos)
            return super()._admit_fork(r_target, req)
        finally:
            self._evict_protect.discard(req.prefix)

    def _maybe_finish(self, r: int, token: int):
        done = super()._maybe_finish(r, token)
        if done is not None and done.session is None:
            self._free_slot_blocks(r)
        if done is not None:
            stash = self._preempted.pop(done.uid, None)
            if stash is not None:
                # stitch the pre-preemption span back: the consumer
                # sees ONE completion for the original request
                done = dataclasses.replace(
                    done, prompt=stash["prompt"],
                    tokens=stash["tokens"] + done.tokens,
                    logprobs=stash["logprobs"] + done.logprobs)
        return done

    def cancel(self, uid: int) -> bool:
        slot = next((r for r in range(self.slots)
                     if self._req[r] is not None
                     and self._req[r].uid == uid), None)
        ok = super().cancel(uid)
        if ok and slot is not None:
            self._free_slot_blocks(slot)
        if ok:
            self._preempted.pop(uid, None)
        return ok

    def _evict_lru_parked(self, force: bool = False) -> int | None:
        r = super()._evict_lru_parked(force)
        if r is not None:
            self._free_slot_blocks(r)
        return r

    def release(self, sid: int) -> bool:
        entry = self._parked.get(sid)
        ok = super().release(sid)
        if ok and entry is not None:
            self._free_slot_blocks(entry[0])
        return ok

    def _check_request(self, prompt_len: int, max_new_tokens: int) -> None:
        super()._check_request(prompt_len, max_new_tokens)
        if self._blocks_needed(prompt_len + max_new_tokens) > self._nblk:
            raise ValueError(
                f"request needs {self._blocks_needed(prompt_len + max_new_tokens)} "
                f"KV blocks but the pool holds {self._nblk} — raise "
                "page_blocks")

    def _reclaimable_blocks(self) -> int:
        """Blocks that LRU eviction could free right now — parked
        entries not referenced by queued continuations, counting only
        their sole-owner (refcount-1) blocks."""
        queued = {q.session for q in self.queue if q.session is not None}
        queued |= {q.prefix for q in self.queue if q.prefix is not None}
        reclaimable = 0
        for sid, (r, _, _) in self._parked.items():
            if sid in queued or sid in self._evict_protect:
                continue
            reclaimable += sum(
                1 for j in range(int(self._nalloc[r]))
                if self._refcnt[int(self._tables[r, j])] == 1)
        return reclaimable

    def can_preload(self, prompt_len: int | None = None) -> bool:
        """Slot capacity AND block capacity: a free slot is worthless
        if the pool cannot hold the template — preload() would raise
        pool-exhausted and the caller's graceful fallback (n plain
        submits) would never engage."""
        if not super().can_preload():
            return False
        need = (self._blocks_needed(prompt_len)
                if prompt_len is not None else 1)
        return len(self._free_list) + self._reclaimable_blocks() >= need

    def _can_admit(self, req: Request) -> bool:
        """Block-budget admission gate: while other requests are
        draining, a fresh request waits until the pool can hold its
        prompt + first decode block — admitting early would just
        preempt it (or someone else) immediately. With nothing active
        the gate opens unconditionally: nothing will ever drain, so
        admission must proceed and _ensure_blocks either reclaims
        (evict/preempt) or raises the honest exhaustion error."""
        if not self.active_slots:
            return True
        # a fork's prompt is just its turn remainder (the template is
        # shared/aliased); +1 covers the possible partial-block copy
        need = self._blocks_needed(len(req.prompt) + 1) + (
            1 if req.prefix is not None else 0)
        return len(self._free_list) + self._reclaimable_blocks() >= need

    # -------------------------------------------------- batched steps
    def _decode(self, ids):
        for r in self.active_slots:
            if self._req[r] is None:
                continue  # preempted by an earlier row's _ensure_blocks
            self._ensure_blocks(r, int(self._pos[r]) + 1)
        logits, self.cache = _paged_decode_step(
            self.model, self.params, self.cache, ids,
            jnp.asarray(self._tables))
        return logits

    def _decode_multi(self, ids):
        S = int(ids.shape[1])
        for r in self.active_slots:
            if self._req[r] is None:
                continue  # preempted by an earlier row's _ensure_blocks
            self._ensure_blocks(r, int(self._pos[r]) + S)
        logits, self.cache = _paged_decode_multi(
            self._model_multi, self.params, self.cache, ids,
            jnp.asarray(self._tables))
        return logits

    def new_tokens_since(self, seen: dict[int, int]) -> dict[int, list[int]]:
        """Preemption-aware streaming tap: a consumer's seen-count is
        ABSOLUTE over the request's full output, but a requeued
        request's _generated restarts after its committed span folded
        into the prompt — so index into stash + generated, keeping
        deltas gap- and duplicate-free across preemptions."""
        out: dict[int, list[int]] = {}
        for r in self.active_slots:
            uid = self._req[r].uid
            n = seen.get(uid)
            if n is None:
                continue
            stash = self._preempted.get(uid)
            full = (stash["tokens"] + self._generated[r]
                    if stash else self._generated[r])
            if len(full) > n:
                out[uid] = full[n:]
        return out


# ------------------------------------------------------ seq2seq (t5) serving

@partial(jax.jit, donate_argnums=(0, 1))
def _insert_enc_row(enc_buf, mask_buf, enc_row, mask_row, r):
    """Write a freshly encoded B=1 source into slot ``r`` of the encoder
    pool. ``enc_row`` is bucket-length; columns beyond it keep the old
    occupant's values but ``mask_row`` (full source-cap width, zeros past
    the new source) makes them invisible to cross-attention."""
    enc_buf = jax.lax.dynamic_update_slice(
        enc_buf, enc_row.astype(enc_buf.dtype), (r, 0, 0))
    mask_buf = jax.lax.dynamic_update_slice(mask_buf, mask_row, (r, 0))
    return enc_buf, mask_buf


class Seq2SeqContinuousBatcher(ContinuousBatcher):
    _count_prompt = False

    """Continuous batching for encoder-decoder (t5) models.

    A submitted ``prompt`` is the SOURCE sequence: admission encodes it
    once at B=1 (padded to a power-of-two bucket), scatters the encoder
    rows into a static (slots, source_cap, C) pool, and zeroes the slot's
    decoder cache row. Decoding then advances every slot one target token
    per batched step exactly like the causal batcher — per-row decoder
    cache offsets (models/t5.py decode_rows), fixed per-slot encoder rows,
    cross-attention masked to each slot's true source length. T5
    conventions by default: the decoder starts from pad id 0; pass
    ``eos_id=1`` per request to stop at T5's EOS.
    """

    supports_sessions = False  # the decoder restarts per request

    def __init__(self, model_cfg: ModelConfig, precision: PrecisionConfig,
                 params: Any, *, slots: int = 4, top_k: int = 0,
                 top_p: float = 0.0, min_p: float = 0.0, rng=None,
                 min_bucket: int = 16, source_cap: int = 0,
                 decoder_start_id: int = 0):
        from pytorch_distributed_train_tpu.models.t5 import (
            t5_decode_step,
            t5_encoder,
        )

        if not model_cfg.name.startswith("t5"):
            raise ValueError(
                f"Seq2SeqContinuousBatcher serves the t5 family, got "
                f"{model_cfg.name!r}")
        dtype = jnp.dtype(precision.compute_dtype)
        param_dtype = jnp.dtype(precision.param_dtype)
        self._init_common(params, slots, top_k, top_p, rng, min_p)
        self.encoder = t5_encoder(model_cfg, dtype, param_dtype)
        self.model = t5_decode_step(model_cfg, dtype, param_dtype,
                                    max_decode_len=model_cfg.max_seq_len,
                                    decode_rows=True)
        self.max_seq_len = model_cfg.max_seq_len
        self.source_cap = source_cap or model_cfg.max_seq_len
        self.decoder_start_id = decoder_start_id
        self._build_buckets(self.source_cap, min_bucket)

        from pytorch_distributed_train_tpu.generate import (
            _seq2seq_cache_shapes,
        )

        self._enc = jnp.zeros((slots, self.source_cap,
                               model_cfg.hidden_size), dtype)
        self._enc_mask = jnp.zeros((slots, self.source_cap), jnp.int32)
        shapes = _seq2seq_cache_shapes(self.model, slots, self._enc.shape,
                                       str(dtype))
        self.cache = jax.tree.map(lambda sh: jnp.zeros(sh.shape, sh.dtype),
                                  shapes)
        # One immutable zero template for decoder-row resets: _insert_row
        # donates only the pool (argnum 0), so reusing this every admit is
        # safe and skips a per-admission KV-tree allocation.
        self._zero_row = jax.tree.map(
            lambda sh: jnp.zeros(sh.shape, sh.dtype),
            _seq2seq_cache_shapes(self.model, 1, (1,) + self._enc.shape[1:],
                                  str(dtype)))
        self._init_slot_state(slots)

    def _check_request(self, prompt_len: int, max_new_tokens: int) -> None:
        if prompt_len > self.source_cap:
            raise ValueError(
                f"source ({prompt_len}) exceeds source_cap "
                f"({self.source_cap})")
        if max_new_tokens + 1 > self.max_seq_len:
            raise ValueError(
                f"max_new_tokens ({max_new_tokens}) + start token exceeds "
                f"max_seq_len ({self.max_seq_len})")

    def _admit(self, r: int, req: Request) -> Completion | None:
        """Encode the source into slot ``r`` and reset its decoder row.
        Unlike the causal batcher, admission emits NO token — the next
        batched step feeds the decoder-start id and samples the first."""
        from pytorch_distributed_train_tpu.generate import _seq2seq_encode

        P = self._bucket(len(req.prompt))
        ids = np.zeros((1, P), np.int32)
        ids[0, : len(req.prompt)] = req.prompt
        mask = np.zeros((1, self.source_cap), np.int32)
        mask[0, : len(req.prompt)] = 1
        enc_row = _seq2seq_encode(self.encoder, self.params,
                                  jnp.asarray(ids),
                                  jnp.asarray(mask[:, :P]))
        self._enc, self._enc_mask = _insert_enc_row(
            self._enc, self._enc_mask, enc_row, jnp.asarray(mask),
            jnp.int32(r))
        self.cache = _insert_row(self.cache, self._zero_row, jnp.int32(r),
                                 jnp.int32(0))
        self.stats["prefills"] += 1
        self._req[r] = req
        self._generated[r] = []
        self._logprobs[r] = []
        self._pending[r] = self.decoder_start_id
        self._temp[r] = req.temperature
        # Penalties score the DECODER stream only (_count_prompt=False —
        # the "prompt" here is the encoder source): start from an empty
        # count row; step() bumps it per emitted token.
        self._set_row_sampling_state(r, req)
        return None  # first token arrives at the next batched step

    def _decode(self, ids):
        from pytorch_distributed_train_tpu.generate import (
            _seq2seq_decode_step,
        )

        logits, self.cache = _seq2seq_decode_step(
            self.model, self.params, self.cache, ids, self._enc,
            self._enc_mask)
        return logits
