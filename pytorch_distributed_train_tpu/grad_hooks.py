"""Gradient-compression hooks as optax transforms (SURVEY C8).

The reference's DDP comm hooks (torch:distributed/algorithms/ddp_comm_hooks/
default_hooks.py fp16_compress_hook, powerSGD_hook.py) intercept each grad
bucket before its NCCL all-reduce: cast to half precision, or project to a
rank-r factorization with error feedback, then communicate the compressed
form. On TPU the gradient collectives are placed by GSPMD inside the
compiled step, so the hook point moves: these transforms run at the same
algorithmic position (on the gradient, before clipping and the optimizer)
and reproduce the hooks' numerics — the quantization/low-rank error and the
error-feedback correction the model actually trains under. The wire-format
saving of the torch hooks is an NCCL-runtime concern with no analogue here;
XLA already fuses grad reduction into the backward schedule.

Use via ``OptimConfig.grad_hook``: "none" | "bf16" | "fp16" | "powersgd".
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax


def compress(dtype: str) -> optax.GradientTransformation:
    """Half-precision compression: grad → dtype → fp32 (the fp16/bf16
    compress hook's quantization, default_hooks.py)."""
    target = jnp.dtype(dtype)

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        updates = jax.tree.map(
            lambda g: g.astype(target).astype(jnp.float32), updates
        )
        return updates, state

    return optax.GradientTransformation(init_fn, update_fn)


class PowerSGDState(NamedTuple):
    q: dict  # per-leaf rank-r right factors (None for passthrough leaves)
    error: dict  # per-leaf error-feedback residuals


def _is_matrix(shape: tuple[int, ...]) -> bool:
    return len(shape) >= 2 and min(shape[0], int(np.prod(shape[1:]))) > 1


def _as_2d(g: jnp.ndarray) -> jnp.ndarray:
    return g.reshape(g.shape[0], -1)


def powersgd(rank: int = 2, seed: int = 0) -> optax.GradientTransformation:
    """PowerSGD low-rank compression with error feedback (powerSGD_hook.py,
    after Vogels et al. 2019).

    Per matrix-shaped grad G (m×n, reshaped from the leaf): with persistent
    right factor Q (n×r), one subspace-iteration step
        P = orth(（G+e) Q);  Q' = (G+e)ᵀ P;  Ĝ = P Q'ᵀ;  e' = (G+e) − Ĝ
    replaces G by its rank-r approximation Ĝ; the residual e carries the
    compression error into the next step (what makes PowerSGD converge).
    Vectors/scalars pass through uncompressed, as in the torch hook.
    """

    def init_fn(params):
        leaves, treedef = jax.tree_util.tree_flatten(params)
        key = jax.random.PRNGKey(seed)
        keys = jax.random.split(key, max(len(leaves), 1))
        qs, errs = [], []
        for k, p in zip(keys, leaves):
            if _is_matrix(p.shape):
                n = int(np.prod(p.shape[1:]))
                r = min(rank, p.shape[0], n)
                qs.append(jax.random.normal(k, (n, r), jnp.float32))
                errs.append(jnp.zeros(p.shape, jnp.float32))
            else:
                qs.append(None)
                errs.append(None)
        return PowerSGDState(
            q=jax.tree_util.tree_unflatten(treedef, qs),
            error=jax.tree_util.tree_unflatten(treedef, errs),
        )

    def _one(g, q, e):
        if q is None:
            return g, None, None
        g2 = _as_2d(g.astype(jnp.float32)) + _as_2d(e)
        p = g2 @ q  # (m, r)
        p, _ = jnp.linalg.qr(p)  # orthonormalize (the hook's Gram-Schmidt)
        q_new = g2.T @ p  # (n, r)
        g_hat = p @ q_new.T
        e_new = (g2 - g_hat).reshape(g.shape)
        return g_hat.reshape(g.shape).astype(g.dtype), q_new, e_new

    def update_fn(updates, state, params=None):
        del params
        u_leaves, treedef = jax.tree_util.tree_flatten(updates)
        q_leaves = treedef.flatten_up_to(state.q)
        e_leaves = treedef.flatten_up_to(state.error)
        outs = [_one(g, q, e) for g, q, e in zip(u_leaves, q_leaves, e_leaves)]
        new_u = jax.tree_util.tree_unflatten(treedef, [o[0] for o in outs])
        new_q = jax.tree_util.tree_unflatten(treedef, [o[1] for o in outs])
        new_e = jax.tree_util.tree_unflatten(treedef, [o[2] for o in outs])
        return new_u, PowerSGDState(q=new_q, error=new_e)

    return optax.GradientTransformation(init_fn, update_fn)


def get_hook(name: str, *, powersgd_rank: int = 2,
             seed: int = 0) -> optax.GradientTransformation | None:
    if name in ("", "none"):
        return None
    if name in ("bf16", "bfloat16"):
        return compress("bfloat16")
    if name in ("fp16", "float16"):
        return compress("float16")
    if name == "powersgd":
        return powersgd(rank=powersgd_rank, seed=seed)
    raise ValueError(f"unknown grad_hook {name!r}")
