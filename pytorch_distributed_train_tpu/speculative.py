"""Speculative decoding: draft-model proposal + single-pass verification.

Serving-side latency optimization (beyond the reference's scope — its
harness has no inference path at all; this extends generate.py the way
vLLM/HF extend torch serving): a small DRAFT model proposes ``k`` tokens
autoregressively, then the large TARGET model scores all k in ONE
multi-token forward and accepts a prefix of them. Exact-sampling
acceptance (Leviathan et al. 2023, "Fast Inference from Transformers via
Speculative Decoding"): token d_i is accepted with probability
min(1, p_target(d_i)/p_draft(d_i)); on the first rejection a replacement
is drawn from the residual distribution norm(max(p_t - p_d, 0)). The
emitted token stream is distributed EXACTLY as target-only sampling —
the draft only changes how many target forwards are needed, never the
output law. With temperature=0 both laws are argmax, so acceptance is
"draft token == target argmax" and output equals greedy target decoding
token-for-token.

Why this fits the TPU decode regime: single-token decode steps are
HBM-bandwidth-bound (every step streams all weights for one token of
compute), so a k+1-token verify forward costs nearly the same wall-clock
as a 1-token step — the MXU is idle either way; accepted tokens are
almost free. All device work is jit-compiled with static shapes: the
draft loop is k single-token steps, verification is one (1, k+1) call on
the ``decode_multi`` continuation path (models/llama.py), and the
accept/resample decision is a fused kernel returning (n_accepted,
next_token). Only the Python round loop sees the dynamic acceptance
count — it rolls the static KV caches back by resetting their
``cache_index`` scalars (stale tail entries are position-masked, so a
rewound index fully invalidates them).

Batch is restricted to B=1: per-row acceptance counts would need per-row
cache indices, and latency-bound serving (the regime where speculative
decoding pays) is B=1 anyway.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from flax import traverse_util

from pytorch_distributed_train_tpu.generate import (
    build_decode_model,
    filter_logits,
    init_cache,
)


def _filtered_probs(logits, temperature: float, top_k: int,
                    top_p: float = 0.0, min_p: float = 0.0):
    """Temperature/top-k/top-p-adjusted probabilities. Both models' laws
    are modified identically — via generate.filter_logits, the SAME
    filtering generate() samples with — and spec sampling is exact w.r.t.
    the modified target law (the standard convention). logits: (..., V)."""
    return jax.nn.softmax(
        filter_logits(logits, temperature, top_k, top_p, min_p), axis=-1)


@partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def _step_logits(model, params, cache, ids):
    """One decode forward (any static S); returns per-position logits."""
    from pytorch_distributed_train_tpu import quant

    params = quant.dequantize_tree(params, model.dtype)
    logits, updated = model.apply(
        {"params": params, "cache": cache}, ids, train=False,
        mutable=["cache"],
    )
    return logits, updated["cache"]


@partial(jax.jit, static_argnums=(2, 3, 4, 5))
def _draft_sample(logits_last, rng, temperature: float, top_k: int,
                  top_p: float = 0.0, min_p: float = 0.0):
    """One fused dispatch per proposed token: (token, draft probs)."""
    if temperature == 0.0:
        # _accept's greedy branch never reads p_draft — skip the
        # full-vocab softmax and return a placeholder.
        return (jnp.argmax(logits_last).astype(jnp.int32),
                jnp.zeros((logits_last.shape[-1],), jnp.float32))
    p = _filtered_probs(logits_last, temperature, top_k, top_p, min_p)
    tok = jax.random.categorical(
        rng, jnp.log(jnp.maximum(p, 1e-30))).astype(jnp.int32)
    return tok, p


@partial(jax.jit, static_argnums=(3, 4, 5, 7, 8))
def _accept(rng, draft_tokens, p_draft, k: int, temperature: float,
            top_k: int, t_logits, top_p: float = 0.0,
            min_p: float = 0.0):
    """The accept/resample decision, fused on device.

    draft_tokens: (k,) int32; p_draft: (k, V) draft probabilities for the
    positions that produced each draft token; t_logits: (k+1, V) target
    logits — row i is the target's next-token distribution at the
    position where draft_tokens[i] was proposed, row k is the bonus
    position after all k drafts.

    Returns (n_accepted, next_token): n in [0, k]; next_token is the
    residual resample when n < k, the bonus sample when n == k.
    """
    greedy = temperature == 0.0
    if greedy:
        t_choice = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)  # (k+1,)
        accept = t_choice[:k] == draft_tokens
        n = jnp.sum(jnp.cumprod(accept.astype(jnp.int32)))
        # rejected → the target's own argmax at position n; all accepted
        # → bonus argmax. Both are t_choice[n].
        return n, t_choice[n]
    p_t = _filtered_probs(t_logits, temperature, top_k, top_p,
                          min_p)  # (k+1, V)
    p_t_k = p_t[:k]
    rng_u, rng_res, rng_bonus = jax.random.split(rng, 3)
    p_d_tok = jnp.take_along_axis(
        p_draft, draft_tokens[:, None], axis=-1)[:, 0]
    p_t_tok = jnp.take_along_axis(
        p_t_k, draft_tokens[:, None], axis=-1)[:, 0]
    u = jax.random.uniform(rng_u, (k,))
    accept = u * p_d_tok < p_t_tok  # u < p_t/p_d without the div-by-zero
    n = jnp.sum(jnp.cumprod(accept.astype(jnp.int32)))
    # Residual at the first rejected position (row n when n < k; row
    # clamped to k-1 is dead when n == k). max(p_t - p_d, 0) renormalized;
    # if the residual is numerically all-zero (p_t == p_d) fall back to p_t.
    row = jnp.minimum(n, k - 1)
    residual = jnp.maximum(p_t_k[row] - p_draft[row], 0.0)
    mass = jnp.sum(residual)
    residual = jnp.where(mass > 0, residual / jnp.maximum(mass, 1e-20),
                         p_t_k[row])
    resampled = jax.random.categorical(
        rng_res, jnp.log(jnp.maximum(residual, 1e-30)))
    bonus = jax.random.categorical(
        rng_bonus, jnp.log(jnp.maximum(p_t[k], 1e-30)))
    nxt = jnp.where(n < k, resampled, bonus).astype(jnp.int32)
    return n, nxt


def _set_cache_index(cache, idx: int):
    """Roll a static KV cache to ``idx`` committed tokens. Entries past
    the index are stale but position-masked (models/llama.py builds the
    decode mask from cache_index, not buffer contents), so resetting the
    per-layer index scalars IS the rollback. ``pos_index`` is gpt2's
    learned-position counter (models/gpt2.py) — same discipline."""
    flat = traverse_util.flatten_dict(cache, sep="/")
    for path in flat:
        if path.rsplit("/", 1)[-1] in ("cache_index", "pos_index"):
            flat[path] = jnp.full((), idx, jnp.int32)
    return traverse_util.unflatten_dict(flat, sep="/")


def speculative_generate(model_cfg, precision, params,
                         draft_model_cfg, draft_params,
                         prompt_ids, max_new_tokens: int,
                         *, k: int = 4, temperature: float = 0.0,
                         top_k: int = 0, top_p: float = 0.0,
                         min_p: float = 0.0, rng=None,
                         eos_id: int | None = None,
                         return_stats: bool = False):
    """Generate ``max_new_tokens`` continuation tokens for a (1, S)
    prompt, distributed exactly as target-only sampling.

    ``model_cfg``/``draft_model_cfg`` are ModelConfigs (llama family —
    the decode-mode models are built here, both sharing a vocabulary);
    ``params``/``draft_params`` their trained param trees. ``k`` is the
    speculation depth: each round costs k draft forwards + 1 target
    forward and commits between 1 and k+1 tokens.
    """
    target = build_decode_model(model_cfg, precision)
    draft = build_decode_model(draft_model_cfg, precision)
    if model_cfg.vocab_size != draft_model_cfg.vocab_size:
        raise ValueError(
            f"target vocab ({model_cfg.vocab_size}) != draft vocab "
            f"({draft_model_cfg.vocab_size}) — speculative decoding "
            "compares per-token distributions, the vocabularies must match")
    import dataclasses

    target_multi = dataclasses.replace(target, decode_multi=True)
    draft_multi = dataclasses.replace(draft, decode_multi=True)

    prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
    B, S = prompt_ids.shape
    if B != 1:
        raise ValueError(
            f"speculative decoding is B=1 (got B={B}): acceptance length "
            "varies per row, and the static KV cache has one index")
    horizon = S + max_new_tokens + k + 1
    for label, limit in (("target", model_cfg.max_seq_len),
                         ("draft", draft_model_cfg.max_seq_len)):
        # Both caches walk the full sequence; an overrun would clamp the
        # dynamic KV writes onto the last slot silently, not error.
        if horizon > limit:
            raise ValueError(
                f"prompt ({S}) + new ({max_new_tokens}) + speculation "
                f"margin ({k + 1}) exceeds {label} max_seq_len ({limit})")
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    tokens = [int(t) for t in prompt_ids[0]]
    t_cache = init_cache(target, 1)
    d_cache = init_cache(draft, 1)
    if S > 1:
        # Prefill both caches with the prompt MINUS its last token — the
        # last token is the round loop's pending input (its KV is written
        # by the round that consumes it).
        _, t_cache = _step_logits(target, params, t_cache,
                                  prompt_ids[:, :-1])
        _, d_cache = _step_logits(draft, draft_params, d_cache,
                                  prompt_ids[:, :-1])
    d_valid = S - 1  # committed tokens whose KV the draft cache holds
    produced = 0
    rounds = accepted_total = 0

    while produced < max_new_tokens:
        C = len(tokens) - 1  # committed-and-cached (target view); tokens[-1] pending
        # ---- draft k proposals (first step flushes any tokens the draft
        # cache missed — at most 1, when the previous round accepted all k)
        d_in = jnp.asarray([tokens[d_valid:]], jnp.int32)  # (1, 1 or 2)
        d_model = draft if d_in.shape[1] == 1 else draft_multi
        logits, d_cache = _step_logits(d_model, draft_params, d_cache, d_in)
        draft_tokens = []
        draft_probs = []
        for i in range(k):
            rng, r = jax.random.split(rng)
            tok, p = _draft_sample(logits[0, -1], r, temperature, top_k,
                                   top_p, min_p)
            draft_tokens.append(tok)
            draft_probs.append(p)
            if i + 1 < k:  # d_k's own forward is never needed this round
                logits, d_cache = _step_logits(
                    draft, draft_params, d_cache, tok[None, None])
        draft_vec = jnp.stack(draft_tokens)
        p_draft = jnp.stack(draft_probs)

        # ---- verify: one (1, k+1) target forward at the running offset
        v_in = jnp.concatenate(
            [jnp.asarray([tokens[-1]], jnp.int32), draft_vec])[None, :]
        t_logits, t_cache = _step_logits(
            target_multi, params, t_cache, v_in)
        rng, r = jax.random.split(rng)
        n, nxt = _accept(r, draft_vec, p_draft, k, temperature, top_k,
                         t_logits[0].astype(jnp.float32), top_p, min_p)
        n = int(n)

        # ---- commit + roll both caches back to the accepted prefix
        new_tokens = [int(t) for t in draft_vec[:n]] + [int(nxt)]
        tokens.extend(new_tokens)
        produced += len(new_tokens)
        rounds += 1
        accepted_total += n
        # target wrote k+1 KVs (pending + k drafts); valid prefix is
        # pending + n accepted → C + 1 + n. tokens[-1] is the new pending.
        t_cache = _set_cache_index(t_cache, C + 1 + n)
        # draft wrote len(d_in) + (k-1) KVs, covering committed tokens up
        # to d_{k-1} — everything accepted except a fully-accepted d_k.
        d_valid = min(C + 1 + n, C + k)
        d_cache = _set_cache_index(d_cache, d_valid)
        if eos_id is not None and eos_id in new_tokens:
            cut = len(tokens) - len(new_tokens) + new_tokens.index(eos_id)
            tokens = tokens[: cut + 1]
            break

    tokens = tokens[: S + max_new_tokens]
    if eos_id is not None and len(tokens) < S + max_new_tokens:
        tokens += [eos_id] * (S + max_new_tokens - len(tokens))
    out = jnp.asarray([tokens], jnp.int32)
    if return_stats:
        return out, {
            "rounds": rounds,
            "accept_rate": accepted_total / max(rounds * k, 1),
            "tokens_per_round": (len(tokens) - S) / max(rounds, 1),
        }
    return out


# --------------------------------------------------- prompt-lookup variant

def propose_from_context(tokens: list[int], k: int, ngram: int) -> list[int] | None:
    """Prompt-lookup proposal (vLLM's ngram speculator / HF
    prompt_lookup_num_tokens): find the MOST RECENT earlier occurrence of
    the trailing ``ngram`` tokens in the context and copy the k tokens
    that followed it. Returns None when no earlier occurrence (with at
    least one following token) exists. Host-side list matching — B=1 and
    a few hundred tokens; the device never sees this."""
    if len(tokens) <= ngram:
        return None
    tail = tokens[-ngram:]
    # newest match first: repetitions late in the text predict better
    for start in range(len(tokens) - ngram - 1, -1, -1):
        if tokens[start:start + ngram] == tail:
            follow = tokens[start + ngram:start + ngram + k]
            if follow:
                # pad a short window by repeating its last token — the
                # verify pass prices k+1 tokens regardless, and wrong
                # tails just reject
                return follow + [follow[-1]] * (k - len(follow))
    return None


def prompt_lookup_generate(model_cfg, precision, params, prompt_ids,
                           max_new_tokens: int, *, k: int = 4,
                           ngram: int = 3, temperature: float = 0.0,
                           top_k: int = 0, top_p: float = 0.0,
                           min_p: float = 0.0, rng=None,
                           eos_id: int | None = None,
                           return_stats: bool = False):
    """Draft-FREE speculative decoding: proposals come from n-gram
    lookup over the sequence's own history instead of a draft model —
    the regime where generation repeats its context (summarization,
    code edits, RAG answers quoting sources) gets multi-token commits
    for zero extra model cost.

    Exactness: a lookup proposal is a POINT MASS, and the Leviathan
    accept/resample rule with p_draft = one_hot(d_i) reduces to "accept
    d_i with prob p_target(d_i), else resample from p_target with d_i
    zeroed out" — still exactly the target-only law (the shared _accept
    kernel is reused with one-hot draft rows). Greedy: accept while the
    copied token IS the argmax; output equals generate() token-for-token.
    Rounds with no match propose a repeat of the pending token — garbage
    that rejects at position 0, making the round exactly a plain decode
    step at the same bandwidth cost (the k+1-token verify reads the
    weights once, like any step)."""
    import dataclasses

    target = build_decode_model(model_cfg, precision)
    target_multi = dataclasses.replace(target, decode_multi=True)

    prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
    B, S = prompt_ids.shape
    if B != 1:
        raise ValueError(
            f"prompt-lookup decoding is B=1 (got B={B}); see "
            "speculative_generate")
    if ngram < 1 or k < 1:
        raise ValueError(f"need ngram >= 1 and k >= 1, got {ngram}, {k}")
    horizon = S + max_new_tokens + k + 1
    if horizon > model_cfg.max_seq_len:
        raise ValueError(
            f"prompt ({S}) + new ({max_new_tokens}) + speculation margin "
            f"({k + 1}) exceeds max_seq_len ({model_cfg.max_seq_len})")
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    tokens = [int(t) for t in prompt_ids[0]]
    t_cache = init_cache(target, 1)
    if S > 1:
        _, t_cache = _step_logits(target, params, t_cache,
                                  prompt_ids[:, :-1])
    produced = 0
    rounds = accepted_total = matched_rounds = 0
    V = model_cfg.vocab_size

    while produced < max_new_tokens:
        C = len(tokens) - 1  # committed-and-cached; tokens[-1] pending
        proposal = propose_from_context(tokens, k, ngram)
        if proposal is None:
            proposal = [tokens[-1]] * k  # rejects at 0 → plain step
        else:
            matched_rounds += 1
        draft_vec = jnp.asarray(proposal, jnp.int32)
        p_draft = jax.nn.one_hot(draft_vec, V)  # point-mass "draft law"

        v_in = jnp.concatenate(
            [jnp.asarray([tokens[-1]], jnp.int32), draft_vec])[None, :]
        t_logits, t_cache = _step_logits(
            target_multi, params, t_cache, v_in)
        rng, r = jax.random.split(rng)
        n, nxt = _accept(r, draft_vec, p_draft, k, temperature, top_k,
                         t_logits[0].astype(jnp.float32), top_p, min_p)
        n = int(n)

        new_tokens = [int(t) for t in draft_vec[:n]] + [int(nxt)]
        tokens.extend(new_tokens)
        produced += len(new_tokens)
        rounds += 1
        accepted_total += n
        t_cache = _set_cache_index(t_cache, C + 1 + n)
        if eos_id is not None and eos_id in new_tokens:
            cut = len(tokens) - len(new_tokens) + new_tokens.index(eos_id)
            tokens = tokens[: cut + 1]
            break

    tokens = tokens[: S + max_new_tokens]
    if eos_id is not None and len(tokens) < S + max_new_tokens:
        tokens += [eos_id] * (S + max_new_tokens - len(tokens))
    out = jnp.asarray([tokens], jnp.int32)
    if return_stats:
        return out, {
            "rounds": rounds,
            "accept_rate": accepted_total / max(rounds * k, 1),
            "tokens_per_round": (len(tokens) - S) / max(rounds, 1),
            "match_rate": matched_rounds / max(rounds, 1),
        }
    return out
