"""Store resilience plane: bounded, retried, health-tracked launcher-KV ops.

The entire control plane — rendezvous, world membership, liveness
heartbeats, the peer checkpoint tier, replica/obs-endpoint discovery,
profile coordination, the fleet controller — rides ONE launcher KV
store (native/store.py, hosted by node 0). ``elastic.py`` documents it
as a single point of failure; before this plane existed a store
blackout false-blamed healthy hosts as hung, blinded the collector
into ``fleet_stale``, and could stall the step loop inside a heartbeat
publish. :class:`ResilientStore` is the one wrapper every consumer goes
through instead of a raw ``StoreClient`` (enforced by the ``raw-store``
pass of ``python -m tools.analyze``):

- **every op is time-bounded**: the raw client is driven by a private
  worker thread; an op that exceeds its deadline abandons the worker
  (which closes its connection on its own time) and raises
  :class:`StoreOpTimeout` — a wedged TCP send can never wedge a caller;
- **bounded exponential-backoff retry** via ``faults/retry.retry_call``
  (``retries_total{point=store.*}``), reconnecting between attempts;
- **a last-known-good read cache** for the discovery registries
  (replicas, obs endpoints, world): a registry read that fails after
  retries serves the last successful answer instead of an empty list,
  counted in ``store_lkg_reads_total{registry=}``;
- **an ok→degraded→down health state machine** (:class:`StoreHealth`,
  process-global by default — one process talks to one launcher store)
  exported as metrics (``store_op_seconds``, ``store_degraded_total``,
  ``store_health_state``) and journaled under the closed ``store``
  event category, so consumers (liveness monitor, alert engine, fleet
  controller) share one verdict about the control plane itself.

Exception contract (mirrors the raw client): ``get``/``wait`` raise
``TimeoutError`` when the key never appears — the store ANSWERED, so a
key-absent timeout is neither retried nor a health failure. ``OSError``
(including :class:`StoreOpTimeout` and injected ``store.*`` faults)
means the store itself misbehaved: it is retried, and exhaustion both
propagates to the caller and feeds the health machine.

Fault points ``store.get``/``store.set``/``store.add`` (raise) and
``store.latency`` (sleep) are traversed INSIDE the bounded op path, so
outage windows and latency storms injected via ``PDTT_FAULTS`` exercise
exactly the deadline/retry/LKG machinery production outages would.
"""

from __future__ import annotations

import collections
import queue
import threading
import time

from pytorch_distributed_train_tpu.faults import registry as fregistry
from pytorch_distributed_train_tpu.faults.retry import RetryPolicy, retry_call

STATES = ("ok", "degraded", "down")
_STATE_VALUES = {"ok": 0.0, "degraded": 1.0, "down": 2.0}

# op kind -> the fault point it traverses (wait/num_keys are read-shaped,
# delete is write-shaped; the catalog stays the three points the drills
# drive)
_POINT_BY_KIND = {"get": "store.get", "wait": "store.get",
                  "num_keys": "store.get", "set": "store.set",
                  "delete": "store.set", "add": "store.add"}

_GET_DEFAULT_MAX_LEN = 1 << 20


class StoreOpTimeout(OSError):
    """An op exceeded its ResilientStore deadline. Deliberately NOT a
    ``TimeoutError``: that type means "the store answered: no such key",
    this one means "the store did not answer at all" — conflating them
    would turn an outage into a phantom empty registry."""


class _Absent:
    """In-band marker for the raw client's key-absent TimeoutError, so
    the retry loop (``retry_on=(OSError,)``, and TimeoutError IS an
    OSError) never retries a legitimate answer."""

    __slots__ = ("error",)

    def __init__(self, error: TimeoutError):
        self.error = error


def _registry():
    from pytorch_distributed_train_tpu.obs.registry import get_registry

    return get_registry()


# --------------------------------------------------------------- health
class StoreHealth:
    """ok→degraded→down, driven by per-attempt outcomes on a monotonic
    clock: ``degraded_after`` consecutive transport failures degrade,
    failures persisting ``down_after_s`` past the first mark it down,
    any success snaps back to ok. Process-global by default (module
    singleton below); tests inject isolated instances."""

    def __init__(self, *, degraded_after: int = 2, down_after_s: float = 15.0,
                 clock=time.monotonic):
        self.degraded_after = max(1, int(degraded_after))
        self.down_after_s = float(down_after_s)
        self._clock = clock
        self._lock = threading.Lock()
        self.state = "ok"
        self._state_since = clock()
        self._consecutive = 0
        self._first_failure = None
        self._last_error = ""
        self._ops_total = 0
        self._failures_total = 0
        self._durs: collections.deque = collections.deque(maxlen=128)
        self._lkg_refresh: dict[str, float] = {}
        self._lkg_serves: dict[str, int] = {}

    # ------------------------------------------------------- transitions
    def record_success(self, op: str, duration_s: float) -> None:
        with self._lock:
            self._ops_total += 1
            self._durs.append(float(duration_s))
            self._consecutive = 0
            self._first_failure = None
            prev = self.state
            if prev != "ok":
                self.state = "ok"
                self._state_since = self._clock()
        if prev != "ok":
            self._announce(prev, "ok", op, "")
        self._export_gauge()

    def record_failure(self, op: str, err: BaseException) -> None:
        now = self._clock()
        with self._lock:
            self._ops_total += 1
            self._failures_total += 1
            self._consecutive += 1
            self._last_error = f"{type(err).__name__}: {err}"
            if self._first_failure is None:
                self._first_failure = now
            prev = self.state
            new = prev
            if prev == "ok" and self._consecutive >= self.degraded_after:
                new = "degraded"
            if (new == "degraded"
                    and now - self._first_failure >= self.down_after_s):
                new = "down"
            if new != prev:
                self.state = new
                self._state_since = now
            last = self._last_error
        if new != prev:
            self._announce(prev, new, op, last)
        self._export_gauge()

    def _announce(self, prev: str, new: str, op: str, err: str) -> None:
        # outside self._lock: journaling is file I/O under its own lock
        if prev == "ok" and new in ("degraded", "down"):
            _registry().counter(
                "store_degraded_total",
                help="launcher-store health transitions out of ok "
                     "(store_plane.py)").inc()
        name = "recovered" if new == "ok" else new
        try:
            from pytorch_distributed_train_tpu.obs import events as evl

            evl.emit("store", name, prev=prev, op=op, error=err,
                     consecutive=self._consecutive)
        except Exception:
            pass  # diagnostics must never make an outage worse
        print(f"[store] launcher-store health {prev} -> {new}"
              + (f" ({err})" if err else ""), flush=True)

    def _export_gauge(self) -> None:
        try:
            _registry().gauge(
                "store_health_state",
                help="launcher-store health (0=ok 1=degraded 2=down)"
            ).set(_STATE_VALUES[self.state])
        except Exception:
            pass

    # ------------------------------------------------------------- reads
    def ok(self) -> bool:
        return self.state == "ok"

    def note_lkg_refresh(self, name: str) -> None:
        with self._lock:
            self._lkg_refresh[name] = self._clock()

    def note_lkg_serve(self, name: str) -> None:
        with self._lock:
            self._lkg_serves[name] = self._lkg_serves.get(name, 0) + 1

    def snapshot(self) -> dict:
        """One dict every consumer renders from (fleet_console's store
        line, obs_report, the alert engine's synthetic store target)."""
        now = self._clock()
        with self._lock:
            durs = sorted(self._durs)
            p95 = durs[int(0.95 * (len(durs) - 1))] if durs else 0.0
            ages = {k: round(now - v, 1)
                    for k, v in self._lkg_refresh.items()}
            return {"state": self.state,
                    "state_age_s": round(now - self._state_since, 1),
                    "ops_total": self._ops_total,
                    "failures_total": self._failures_total,
                    "consecutive_failures": self._consecutive,
                    "op_p95_ms": round(p95 * 1000.0, 2),
                    "last_error": self._last_error,
                    "lkg_age_s": ages,
                    "lkg_serves": dict(self._lkg_serves)}


_HEALTH = StoreHealth()


def get_health() -> StoreHealth:
    """The process-global health machine every default-constructed
    ResilientStore feeds (one process, one launcher store)."""
    return _HEALTH


def health_snapshot() -> dict:
    return _HEALTH.snapshot()


def _reset_for_tests() -> None:
    global _HEALTH
    _HEALTH = StoreHealth()


# --------------------------------------------------------- bounded runner
class _Op:
    __slots__ = ("fn", "done", "result", "error")

    def __init__(self, fn):
        self.fn = fn
        self.done = threading.Event()
        self.result = None
        self.error: BaseException | None = None


class _Worker:
    """Owns ONE raw client, executes ops serially. Abandoned (not
    joined) on a deadline miss: it finishes the wedged C call on its own
    time, sees the flag, closes its connection and exits — the caller
    never blocks on a socket it cannot interrupt."""

    def __init__(self, factory, name: str):
        self._factory = factory
        self._q: queue.Queue = queue.Queue()
        self._abandoned = threading.Event()
        self.dead = False
        self._t = threading.Thread(target=self._loop, daemon=True,
                                   name=f"{name}-op")
        self._t.start()

    def submit(self, op: _Op) -> None:
        self._q.put(op)

    def abandon(self) -> None:
        self.dead = True
        self._abandoned.set()
        self._q.put(None)  # unblock an idle get()

    def _loop(self) -> None:
        client = None
        try:
            while not self._abandoned.is_set():
                op = self._q.get()
                if op is None:
                    break
                try:
                    if client is None:
                        client = self._factory()
                    if client is None:
                        raise ConnectionError(
                            "no launcher store (factory returned None)")
                    op.result = op.fn(client)
                except BaseException as e:
                    op.error = e
                    if isinstance(e, OSError) and not isinstance(
                            e, TimeoutError):
                        # transport failure: this connection is suspect;
                        # reconnect on the next op
                        if client is not None:
                            try:
                                client.close()
                            except Exception:
                                pass
                            client = None
                finally:
                    op.done.set()
        finally:
            self.dead = True
            if client is not None:
                try:
                    client.close()
                except Exception:
                    pass


class _OpRunner:
    def __init__(self, factory, name: str):
        self._factory = factory
        self._name = name
        self._lock = threading.Lock()
        self._worker: _Worker | None = None

    def run(self, fn, timeout_s: float):
        with self._lock:
            w = self._worker
            if w is None or w.dead:
                w = _Worker(self._factory, self._name)
                self._worker = w
        op = _Op(fn)
        w.submit(op)
        if not op.done.wait(timeout_s):
            w.abandon()
            with self._lock:
                if self._worker is w:
                    self._worker = None
            raise StoreOpTimeout(
                f"{self._name}: store op exceeded its "
                f"{timeout_s:.1f}s deadline")
        if op.error is not None:
            raise op.error
        return op.result

    def close(self) -> None:
        with self._lock:
            w, self._worker = self._worker, None
        if w is not None:
            w.abandon()


# --------------------------------------------------------- the wrapper
class ResilientStore:
    """Drop-in StoreClient facade (set/get/add/wait/delete/num_keys/
    barrier/close) with the resilience contract from the module doc.

    ``factory`` returns a NEW raw client per call (the worker_store
    convention) — reconnection between retry attempts needs a factory,
    not a client. ``None`` defaults to ``elastic.worker_store``.
    """

    def __init__(self, factory=None, *, op_timeout_s: float = 2.0,
                 policy: RetryPolicy | None = None,
                 health: StoreHealth | None = None, name: str = "store"):
        if factory is None:
            from pytorch_distributed_train_tpu.elastic import worker_store

            factory = worker_store
        self._runner = _OpRunner(factory, name)
        self.op_timeout_s = float(op_timeout_s)
        self._policy = policy or RetryPolicy(
            max_attempts=3, base_delay_s=0.05, max_delay_s=0.5,
            jitter=0.5, retry_on=(OSError,))
        self.health = health if health is not None else get_health()
        self._cache_lock = threading.Lock()
        self._cache: dict[str, object] = {}

    # ------------------------------------------------------------ op core
    def _op(self, kind: str, fn, *, budget_s: float = 0.0):
        """One logical op: fault traversal + deadline + retry + health.
        ``budget_s`` extends the deadline by the op's own legitimate
        blocking budget (a get/wait's timeout_ms is WAITING, not
        latency)."""
        point = _POINT_BY_KIND[kind]
        deadline_s = self.op_timeout_s + float(budget_s)
        hist = _registry().histogram(
            "store_op_seconds", labels={"op": kind},
            help="launcher-store op latency through ResilientStore, "
                 "per attempt")

        def raw(client):
            fregistry.maybe_fire("store.latency")
            fregistry.maybe_fire(point)
            try:
                return fn(client)
            except TimeoutError as e:
                return _Absent(e)  # the store ANSWERED: not a failure

        def attempt():
            t0 = time.perf_counter()
            try:
                out = self._runner.run(raw, deadline_s)
            except OSError as e:
                hist.observe(time.perf_counter() - t0)
                self.health.record_failure(kind, e)
                raise
            dur = time.perf_counter() - t0
            hist.observe(dur)
            self.health.record_success(kind, dur)
            return out

        out = retry_call(attempt, policy=self._policy, point=point)
        if isinstance(out, _Absent):
            raise TimeoutError(str(out.error))
        return out

    # --------------------------------------------------- client surface
    def set(self, key: str, value: bytes) -> None:
        self._op("set", lambda c: c.set(key, value))

    def get(self, key: str, timeout_ms: int = 60_000,
            max_len: int = _GET_DEFAULT_MAX_LEN) -> bytes:
        def fn(c):
            if max_len != _GET_DEFAULT_MAX_LEN:
                return c.get(key, timeout_ms=timeout_ms, max_len=max_len)
            # default max_len stays implicit so duck-typed test fakes
            # only need get(key, timeout_ms=)
            return c.get(key, timeout_ms=timeout_ms)

        return self._op("get", fn, budget_s=timeout_ms / 1000.0)

    def add(self, key: str, delta: int = 1) -> int:
        return self._op("add", lambda c: c.add(key, delta))

    def wait(self, key: str, timeout_ms: int = 60_000) -> None:
        self._op("wait", lambda c: c.wait(key, timeout_ms=timeout_ms),
                 budget_s=timeout_ms / 1000.0)

    def delete(self, key: str) -> None:
        self._op("delete", lambda c: c.delete(key))

    def num_keys(self) -> int:
        return self._op("num_keys", lambda c: c.num_keys())

    def barrier(self, name: str, world: int, rank: int,
                timeout_ms: int = 60_000) -> None:
        n = self.add(f"barrier/{name}/count", 1)
        if n == world:
            self.set(f"barrier/{name}/go", b"1")
        self.wait(f"barrier/{name}/go", timeout_ms)

    def close(self) -> None:
        self._runner.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ----------------------------------------------------- LKG registry
    def cached(self, name: str, fetch):
        """Run ``fetch()`` (a strict discovery read built on this
        store); success refreshes the named last-known-good entry,
        transport failure serves the cached answer (counted in
        ``store_lkg_reads_total{registry=}``) or re-raises when there
        has never been one."""
        try:
            val = fetch()
        except OSError as e:
            if isinstance(e, TimeoutError) and not isinstance(
                    e, StoreOpTimeout):
                raise  # key-absent is an answer, not an outage
            with self._cache_lock:
                if name not in self._cache:
                    raise
                val = self._cache[name]
            _registry().counter(
                "store_lkg_reads_total", labels={"registry": name},
                help="discovery reads served from the last-known-good "
                     "cache during store degradation").inc()
            self.health.note_lkg_serve(name)
            return val
        with self._cache_lock:
            self._cache[name] = val
        self.health.note_lkg_refresh(name)
        return val

    def discover_replicas(self) -> list:
        from pytorch_distributed_train_tpu import elastic

        return self.cached(
            "replicas", lambda: elastic.discover_replicas(self, strict=True))

    def discover_obs_endpoints(self) -> list:
        from pytorch_distributed_train_tpu import elastic

        return self.cached(
            "obs_endpoints",
            lambda: elastic.discover_obs_endpoints(self, strict=True))

    def world_max(self, default: int = 0) -> int:
        from pytorch_distributed_train_tpu import elastic

        def fetch():
            try:
                raw = self.get(elastic.WORLD_MAX_KEY, timeout_ms=50)
            except TimeoutError:
                return int(default)  # never published: an answer
            return max(int(default), int(raw.decode()))

        try:
            return self.cached("world", fetch)
        except (OSError, ValueError):
            return int(default)


def resilient_worker_store(**kw) -> ResilientStore | None:
    """ResilientStore over ``elastic.worker_store``, or None outside a
    tpurun job (no ``TPUSTORE_ADDR``) — the ``worker_store()`` calling
    convention every consumer already follows."""
    import os

    if not os.environ.get("TPUSTORE_ADDR"):
        return None
    return ResilientStore(**kw)
