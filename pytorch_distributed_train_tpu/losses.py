"""Loss functions for the acceptance-matrix workloads.

All losses return (scalar_loss, aux_metrics_dict) with the loss in fp32.
Static-shape discipline throughout: MLM and causal-LM losses weight ALL
positions instead of gathering a dynamic number of masked/valid tokens
(dynamic shapes would force recompilation — SURVEY §7.4.5).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def softmax_xent(logits, batch, *_, label_smoothing: float = 0.0):
    """Classification loss. batch: {'image':…, 'label': (B,) int}.

    ``label_smoothing`` follows torch's CrossEntropyLoss(label_smoothing=)
    semantics (uniform mass over classes). Metrics: top-1 always; top-5
    when the class count allows (the ImageNet recipe's second number).
    """
    labels = batch["label"]
    n_cls = logits.shape[-1]
    if "target_probs" in batch:
        # Soft targets from MixUp/CutMix (ops/mixup.py) — smoothing is
        # already folded into the target rows there; accuracy below stays
        # against the original hard labels.
        loss = optax.softmax_cross_entropy(
            logits, batch["target_probs"]).mean()
    elif label_smoothing > 0.0:
        targets = optax.smooth_labels(
            jax.nn.one_hot(labels, n_cls), label_smoothing)
        loss = optax.softmax_cross_entropy(logits, targets).mean()
    else:
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
    acc = (jnp.argmax(logits, axis=-1) == labels).mean()
    metrics = {"accuracy": acc}
    if n_cls > 5:
        top5 = jax.lax.top_k(logits, 5)[1]  # (B, 5) indices
        metrics["top5_accuracy"] = (top5 == labels[:, None]).any(-1).mean()
    return loss, metrics


def mlm_xent(logits, batch, *_):
    """Masked-LM loss. batch: {'input_ids', 'labels', 'label_weights', ...}.

    `labels` holds original token ids at masked positions (anything
    elsewhere); `label_weights` is 1.0 at the positions that count
    (the reference-era BERT convention — ~15% of tokens, BASELINE.json:10).
    """
    labels = batch["labels"]
    weights = batch["label_weights"].astype(jnp.float32)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    denom = jnp.maximum(weights.sum(), 1.0)
    loss = (per_tok * weights).sum() / denom
    acc = ((jnp.argmax(logits, -1) == labels) * weights).sum() / denom
    return loss, {"mlm_accuracy": acc}


def causal_lm_xent(logits, batch, *_):
    """Next-token loss. batch: {'input_ids': (B,S)}; optional 'loss_mask'.

    Shifts inside the loss (logits[:, :-1] vs ids[:, 1:]) so the data
    pipeline ships one tensor, as the reference's LM collate does.
    """
    ids = batch["input_ids"]
    logits = logits[:, :-1]
    targets = ids[:, 1:]
    weights = batch.get("loss_mask", jnp.ones_like(ids, jnp.float32))[:, 1:]
    weights = weights.astype(jnp.float32)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    denom = jnp.maximum(weights.sum(), 1.0)
    loss = (per_tok * weights).sum() / denom
    return loss, {"perplexity": jnp.exp(jnp.minimum(loss, 20.0))}


def seq2seq_xent(logits, batch, *_):
    """Encoder-decoder LM loss (t5). batch: {'input_ids' (B,Se),
    'decoder_input_ids' (B,Sd), 'labels' (B,Sd)}; optional
    'label_weights' masks target padding. No shift here — the data
    pipeline builds decoder_input_ids as the shifted-right labels (the
    T5 convention), so logits[t] already predicts labels[t]."""
    labels = batch["labels"]
    weights = batch.get("label_weights",
                        jnp.ones_like(labels)).astype(jnp.float32)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    denom = jnp.maximum(weights.sum(), 1.0)
    loss = (per_tok * weights).sum() / denom
    acc = ((jnp.argmax(logits, -1) == labels) * weights).sum() / denom
    return loss, {"perplexity": jnp.exp(jnp.minimum(loss, 20.0)),
                  "token_accuracy": acc}


def fused_causal_lm_xent(out, batch, *_):
    """Loss for models running the fused chunked head (ModelConfig.
    fused_lm_loss): the model already reduced CE inside its head region
    (chunked_causal_ce below) and returns {'loss_sum', 'weight_sum'}
    instead of (B, S, V) logits — which at 32k vocab never materialize.
    """
    loss = out["loss_sum"] / jnp.maximum(out["weight_sum"], 1.0)
    return loss, {"perplexity": jnp.exp(jnp.minimum(loss, 20.0))}


def chunked_causal_ce(x, kernel, input_ids, loss_mask=None,
                      chunk: int = 256, transpose_kernel: bool = False) -> dict:
    """Fused LM-head + cross-entropy over sequence chunks.

    The torch-era pattern materializes logits (B, S, V) and hands them to
    the loss; at Llama vocab (32k) and seq 2048 that is ~2 GB of fp32 HLO
    temps live through the backward (measured, BASELINE.md 2026-07-30).
    Computing ``head_matmul → CE → scalar`` per sequence chunk under
    `jax.checkpoint` keeps one (B, chunk, V) tile live at a time and saves
    only two scalars per chunk; backward recomputes tiles (the same
    FLOPs-for-HBM trade as chunked attention / flash kernels).

    x: (B, S, E) final hidden states (compute dtype); kernel: (E, V) — or
    (V, E) with ``transpose_kernel`` (tied-embedding heads pass the raw
    embedding table so no transposed copy materializes in HBM);
    input_ids: (B, S) — targets are the shift-by-one, as causal_lm_xent.
    Returns {'loss_sum', 'weight_sum'} fp32 scalars.
    """
    xs = x[:, :-1]
    targets = input_ids[:, 1:]
    weights = (loss_mask[:, 1:] if loss_mask is not None
               else jnp.ones_like(targets)).astype(jnp.float32)
    contract = ((x.ndim - 1,), (1,) if transpose_kernel else (0,))

    B, S, E = xs.shape
    n_chunks = -(-S // chunk)
    pad = n_chunks * chunk - S
    if pad:  # padded positions carry weight 0 → contribute nothing
        xs = jnp.pad(xs, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        weights = jnp.pad(weights, ((0, 0), (0, pad)))
    tiles = (
        xs.reshape(B, n_chunks, chunk, E).transpose(1, 0, 2, 3),
        targets.reshape(B, n_chunks, chunk).transpose(1, 0, 2),
        weights.reshape(B, n_chunks, chunk).transpose(1, 0, 2),
    )

    def body(carry, tile):
        xt, tt, wt = tile
        logits = jax.lax.dot_general(
            xt, kernel, (contract, ((), ())),
            preferred_element_type=jnp.float32,
        )
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, tt)
        return (carry[0] + (ce * wt).sum(), carry[1] + wt.sum()), None

    # lax.scan (not a Python unroll): forces chunk-sequential scheduling so
    # peak memory really is ONE tile — unrolled chunks let XLA overlap
    # several chunk backwards and the saving evaporates. checkpoint makes
    # the backward recompute each tile's logits from its saved inputs.
    (loss_sum, weight_sum), _ = jax.lax.scan(
        jax.checkpoint(body), (jnp.float32(0.0), jnp.float32(0.0)), tiles)
    return {"loss_sum": loss_sum, "weight_sum": weight_sum}


def _kd_term(student_logits, teacher_logits, weights, temperature: float):
    """Hinton-style distillation term: T^2 * KL(softmax(t/T) ||
    softmax(s/T)), position-weighted mean. The T^2 factor keeps the KD
    gradient magnitude comparable to the hard loss as T varies (Hinton et
    al. 2015 §2); teacher logits enter under stop_gradient so the graph
    never differentiates through the teacher forward."""
    t = jax.lax.stop_gradient(teacher_logits.astype(jnp.float32))
    s = student_logits.astype(jnp.float32)
    log_p_t = jax.nn.log_softmax(t / temperature, axis=-1)
    log_q_s = jax.nn.log_softmax(s / temperature, axis=-1)
    kl = jnp.sum(jnp.exp(log_p_t) * (log_p_t - log_q_s), axis=-1)
    if weights is None:
        kd = kl.mean()
    else:
        w = weights.astype(jnp.float32)
        kd = (kl * w).sum() / jnp.maximum(w.sum(), 1.0)
    return kd * temperature**2


def make_distill_loss(base_fn, base_name: str, alpha: float,
                      temperature: float):
    """Wrap a base loss with knowledge distillation (distill.py):

        total = alpha * hard_loss + (1 - alpha) * kd_term

    The batch must carry ``teacher_logits`` (same shape as the student's
    logits — steps.make_train_step's ``teacher_fn`` hook adds them). The
    KD positions/weights mirror each base loss's own: all positions for
    classification, ``label_weights`` for MLM, the shifted ``loss_mask``
    for causal LM."""
    if base_name not in ("softmax_xent", "mlm_xent", "causal_lm_xent"):
        raise ValueError(
            f"distillation needs per-position logits; loss {base_name!r} "
            "is unsupported (fused_causal_lm_xent never materializes them)")
    if not 0.0 <= alpha <= 1.0:
        raise ValueError(f"distill.alpha must be in [0, 1], got {alpha}")
    if temperature <= 0.0:
        raise ValueError(
            f"distill.temperature must be > 0, got {temperature}")

    def fn(logits, batch, *args):
        hard, metrics = base_fn(logits, batch, *args)
        t_logits = batch["teacher_logits"]
        if base_name == "softmax_xent":
            s, t, w = logits, t_logits, None
        elif base_name == "mlm_xent":
            s, t, w = logits, t_logits, batch["label_weights"]
        else:  # causal_lm_xent — same shift as the base loss
            s, t = logits[:, :-1], t_logits[:, :-1]
            ids = batch["input_ids"]
            w = batch.get("loss_mask",
                          jnp.ones_like(ids, jnp.float32))[:, 1:]
        kd = _kd_term(s, t, w, temperature)
        total = alpha * hard + (1.0 - alpha) * kd
        return total, {**metrics, "hard_loss": hard, "kd_loss": kd}

    return fn


def make_dpo_loss(beta: float):
    """Direct Preference Optimization (Rafailov et al. 2023) —
    preference fine-tuning without a reward model:

        L = -log sigmoid(beta * [(pi_c - ref_c) - (pi_r - ref_r)])

    where pi/ref are the policy's / frozen reference's summed
    continuation log-probs of the chosen (c) and rejected (r) responses.

    Batch layout (data.datasets.synthetic_dpo / a preference corpus):
    ``input_ids`` (B, 2, S) — dim 1 is [chosen, rejected] —
    ``loss_mask`` (B, 2, S) marking response tokens (prompt masked out).
    The model sees the pair flattened to (2B, S) (steps.model_inputs);
    the frozen reference's logits arrive as ``teacher_logits`` through
    the same teacher hook distillation uses (distill.load_teacher — the
    reference model IS a teacher with a different loss).
    """
    if beta <= 0.0:
        raise ValueError(f"dpo beta must be > 0, got {beta}")

    def seq_logps(logits, ids, mask):
        # next-token logprob of each sequence's masked continuation
        lp = jax.nn.log_softmax(logits[:, :, :-1].astype(jnp.float32), -1)
        tok = jnp.take_along_axis(lp, ids[:, :, 1:, None], axis=-1)[..., 0]
        return (tok * mask[:, :, 1:].astype(jnp.float32)).sum(-1)  # (B, 2)

    def fn(logits, batch, *_):
        ids = batch["input_ids"]            # (B, 2, S)
        B, two, S = ids.shape
        mask = batch.get("loss_mask", jnp.ones_like(ids))
        pi = seq_logps(logits.reshape(B, 2, S, -1), ids, mask)
        ref = seq_logps(
            jax.lax.stop_gradient(
                batch["teacher_logits"]).reshape(B, 2, S, -1), ids, mask)
        margin = beta * ((pi[:, 0] - ref[:, 0]) - (pi[:, 1] - ref[:, 1]))
        loss = -jax.nn.log_sigmoid(margin).mean()
        return loss, {
            "dpo_accuracy": (margin > 0).mean(),
            "reward_margin": margin.mean() / beta,
            "chosen_reward": (pi[:, 0] - ref[:, 0]).mean(),
            "rejected_reward": (pi[:, 1] - ref[:, 1]).mean(),
        }

    return fn


def make_grpo_loss(clip_eps: float = 0.2):
    """Group-relative policy loss over harvested rollouts (online/ —
    the GRPO surrogate of Shao et al. 2024, value-model-free).

    Batch layout (online/rollouts.to_grpo_batch): ``input_ids`` (B, S)
    prompt+completion, ``loss_mask`` (B, S) — 1.0 exactly on the
    SAMPLED completion tokens — and ``advantage`` (B,), the per-prompt-
    group normalized reward ((r - mean) / std over the group: "better
    than the other samples of this prompt" is the whole baseline).

    Per-token surrogate: -advantage * logpi(sampled token), masked and
    token-mean'd. When the batch also carries ``behavior_logprobs``
    (B, S) — the generating policy's per-token logprobs, aligned to the
    same positions — the PPO-style clipped-ratio objective bounds the
    update against off-policy drift (rollouts from version V training
    version V+k); without them the ratio is 1 and this reduces to
    REINFORCE with the group baseline.
    """
    if clip_eps < 0.0:
        raise ValueError(f"grpo clip_eps must be >= 0, got {clip_eps}")

    def fn(logits, batch, *_):
        ids = batch["input_ids"]  # (B, S)
        mask = batch["loss_mask"][:, 1:].astype(jnp.float32)
        lp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), -1)
        logp = jnp.take_along_axis(
            lp, ids[:, 1:, None], axis=-1)[..., 0]  # (B, S-1)
        adv = jax.lax.stop_gradient(
            batch["advantage"].astype(jnp.float32))[:, None]
        if "behavior_logprobs" in batch:
            behavior = jax.lax.stop_gradient(
                batch["behavior_logprobs"][:, 1:].astype(jnp.float32))
            ratio = jnp.exp(logp - behavior)
            surr = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1.0 - clip_eps, 1.0 + clip_eps) * adv)
            per_tok = -surr
        else:
            per_tok = -adv * logp
        denom = jnp.maximum(mask.sum(), 1.0)
        loss = (per_tok * mask).sum() / denom
        aux = {
            "sampled_tokens": mask.sum(),
            "mean_advantage": batch["advantage"].mean(),
            "mean_sample_logp": (logp * mask).sum() / denom,
            # Model-health analytics (obs/model_health.py), free from
            # tensors already in hand. Token entropy of the policy over
            # sampled positions: entropy collapse (policy going
            # deterministic) is the classic RL failure precursor.
            "token_entropy":
                (-(jnp.exp(lp) * lp).sum(-1) * mask).sum() / denom,
        }
        if "behavior_logprobs" in batch:
            # Sampled-token KL estimate to the BEHAVIOR policy
            # (E_behavior[log behavior - log pi] over the sampled
            # tokens): the off-policy drift the clipped ratio bounds —
            # runaway here means rollouts no longer resemble the policy
            # being trained (the kl_runaway alert input).
            aux["kl_behavior"] = ((behavior - logp) * mask).sum() / denom
        return loss, aux

    return fn


LOSSES = {
    "softmax_xent": softmax_xent,
    "mlm_xent": mlm_xent,
    "causal_lm_xent": causal_lm_xent,
    "seq2seq_xent": seq2seq_xent,
    "fused_causal_lm_xent": fused_causal_lm_xent,
}


def get_loss_fn(name: str, label_smoothing: float = 0.0):
    if name not in LOSSES:
        raise KeyError(f"unknown loss {name!r}; have {sorted(LOSSES)}")
    if not 0.0 <= label_smoothing < 1.0:
        raise ValueError(
            f"label_smoothing must be in [0, 1), got {label_smoothing}")
    fn = LOSSES[name]
    if label_smoothing > 0.0:
        if name != "softmax_xent":
            raise ValueError(
                f"label_smoothing is only supported for softmax_xent, "
                f"not {name!r}")
        import functools

        return functools.partial(fn, label_smoothing=label_smoothing)
    return fn
