"""Loss functions for the acceptance-matrix workloads.

All losses return (scalar_loss, aux_metrics_dict) with the loss in fp32.
Static-shape discipline throughout: MLM and causal-LM losses weight ALL
positions instead of gathering a dynamic number of masked/valid tokens
(dynamic shapes would force recompilation — SURVEY §7.4.5).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def softmax_xent(logits, batch, *_, label_smoothing: float = 0.0):
    """Classification loss. batch: {'image':…, 'label': (B,) int}.

    ``label_smoothing`` follows torch's CrossEntropyLoss(label_smoothing=)
    semantics (uniform mass over classes). Metrics: top-1 always; top-5
    when the class count allows (the ImageNet recipe's second number).
    """
    labels = batch["label"]
    n_cls = logits.shape[-1]
    if "target_probs" in batch:
        # Soft targets from MixUp/CutMix (ops/mixup.py) — smoothing is
        # already folded into the target rows there; accuracy below stays
        # against the original hard labels.
        loss = optax.softmax_cross_entropy(
            logits, batch["target_probs"]).mean()
    elif label_smoothing > 0.0:
        targets = optax.smooth_labels(
            jax.nn.one_hot(labels, n_cls), label_smoothing)
        loss = optax.softmax_cross_entropy(logits, targets).mean()
    else:
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
    acc = (jnp.argmax(logits, axis=-1) == labels).mean()
    metrics = {"accuracy": acc}
    if n_cls > 5:
        top5 = jax.lax.top_k(logits, 5)[1]  # (B, 5) indices
        metrics["top5_accuracy"] = (top5 == labels[:, None]).any(-1).mean()
    return loss, metrics


def mlm_xent(logits, batch, *_):
    """Masked-LM loss. batch: {'input_ids', 'labels', 'label_weights', ...}.

    `labels` holds original token ids at masked positions (anything
    elsewhere); `label_weights` is 1.0 at the positions that count
    (the reference-era BERT convention — ~15% of tokens, BASELINE.json:10).
    """
    labels = batch["labels"]
    weights = batch["label_weights"].astype(jnp.float32)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    denom = jnp.maximum(weights.sum(), 1.0)
    loss = (per_tok * weights).sum() / denom
    acc = ((jnp.argmax(logits, -1) == labels) * weights).sum() / denom
    return loss, {"mlm_accuracy": acc}


def causal_lm_xent(logits, batch, *_):
    """Next-token loss. batch: {'input_ids': (B,S)}; optional 'loss_mask'.

    Shifts inside the loss (logits[:, :-1] vs ids[:, 1:]) so the data
    pipeline ships one tensor, as the reference's LM collate does.
    """
    ids = batch["input_ids"]
    logits = logits[:, :-1]
    targets = ids[:, 1:]
    weights = batch.get("loss_mask", jnp.ones_like(ids, jnp.float32))[:, 1:]
    weights = weights.astype(jnp.float32)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    denom = jnp.maximum(weights.sum(), 1.0)
    loss = (per_tok * weights).sum() / denom
    return loss, {"perplexity": jnp.exp(jnp.minimum(loss, 20.0))}


LOSSES = {
    "softmax_xent": softmax_xent,
    "mlm_xent": mlm_xent,
    "causal_lm_xent": causal_lm_xent,
}


def get_loss_fn(name: str, label_smoothing: float = 0.0):
    if name not in LOSSES:
        raise KeyError(f"unknown loss {name!r}; have {sorted(LOSSES)}")
    if not 0.0 <= label_smoothing < 1.0:
        raise ValueError(
            f"label_smoothing must be in [0, 1), got {label_smoothing}")
    fn = LOSSES[name]
    if label_smoothing > 0.0:
        if name != "softmax_xent":
            raise ValueError(
                f"label_smoothing is only supported for softmax_xent, "
                f"not {name!r}")
        import functools

        return functools.partial(fn, label_smoothing=label_smoothing)
    return fn
