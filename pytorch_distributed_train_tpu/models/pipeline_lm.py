"""Pipeline-parallel Llama: the 'llama_pp' registry entry.

The torch analogue splits an nn.Sequential across stage worker processes
(torch:distributed/pipelining/stage.py builds a PipelineStage per rank);
here the decoder blocks are STACKED along a leading layer axis, that axis is
sharded ``P('stage')``, and parallel/pipeline.py runs the microbatch
schedule inside one SPMD program. Embedding, final norm and LM head are
computed outside the pipeline region under plain GSPMD (they are replicated
over 'stage' and sharded over data/fsdp/tensor as usual) — only the block
stack pipelines.

This class is deliberately NOT an nn.Module: stacking per-layer params is a
plain ``jax.vmap`` over the single-block ``init``, and the pipeline body
calls ``block.apply`` as a pure function — no flax lifted-transform
machinery between the schedule and the compiler. It duck-types the
``init``/``apply`` surface the trainer and steps module use.
"""

from __future__ import annotations

from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp

from pytorch_distributed_train_tpu.models.llama import LlamaBlock, RMSNorm
from pytorch_distributed_train_tpu.parallel import pipeline as pipeline_lib


class PipelinedLlama:
    """Llama-2 decoder with the block stack pipelined over 'stage'.

    Param tree (paths drive partition rules, parallel/partition.py):
      params/tok_embed/embedding         (V, C)
      params/blocks/...                  every LlamaBlock leaf with a leading
                                         stacked-layer dim L (sharded 'stage')
      params/final_norm/scale            (C,)
      params/lm_head/kernel              (C, V)
    """

    def __init__(self, cfg, dtype, param_dtype, *, mesh, cp=None,
                 num_microbatches: int = 0, schedule: str = "gpipe",
                 chunks: int = 1):
        S = max(pipeline_lib.num_stages(mesh), 1)
        self.interleaved = schedule == "interleaved"
        self.chunks = max(chunks, 1) if self.interleaved else 1
        denom = S * self.chunks
        if cfg.num_layers % denom != 0:
            raise ValueError(
                f"num_layers {cfg.num_layers} not divisible by "
                f"{S} stages x {self.chunks} chunks"
            )
        moe = None
        if getattr(cfg, "num_experts", 0) > 1:
            if cfg.moe_every != 1:
                # Stacked blocks must share one structure; alternating
                # dense/MoE layers would need two stacks.
                raise ValueError(
                    "llama_pp MoE requires moe_every=1 (every block MoE)"
                )
            from pytorch_distributed_train_tpu.ops.moe import MoeSpec

            moe = MoeSpec(
                num_experts=cfg.num_experts, top_k=cfg.expert_top_k,
                capacity_factor=cfg.expert_capacity_factor,
                aux_weight=cfg.moe_aux_weight,
                zloss_weight=cfg.moe_zloss_weight, every=1,
                router=cfg.moe_router,
            )
        self.moe = moe
        self.cfg = cfg
        self.mesh = mesh
        self.dtype = dtype
        self.param_dtype = param_dtype
        self.num_microbatches = num_microbatches or max(S, 1)
        self.schedule = schedule
        self.embed = nn.Embed(
            cfg.vocab_size, cfg.hidden_size,
            embedding_init=nn.initializers.normal(0.02),
            param_dtype=param_dtype, name="tok_embed",
        )
        self.block = LlamaBlock(
            cfg.num_heads, cfg.num_kv_heads or cfg.num_heads, cfg.mlp_dim,
            cfg.rope_theta, getattr(cfg, "rope_scaling", 1.0),
            cfg.max_seq_len, cfg.rms_norm_eps,
            dtype, param_dtype,
            rope_scaling_type=getattr(cfg, "rope_scaling_type", "linear"),
            cp=cp, moe=moe,
            attn_impl=getattr(cfg, "attention_impl", "auto"),
            window=getattr(cfg, "attention_window", 0),
            quant=getattr(cfg, "quant_training", ""),
        )
        self.final_norm = RMSNorm(cfg.rms_norm_eps)
        # bf16 operands + fp32 accumulation: full MXU rate with fp32 logits
        # (same rationale as LlamaForCausalLM's head).
        self.lm_head = nn.Dense(
            cfg.vocab_size, use_bias=False, dtype=dtype,
            param_dtype=param_dtype,
            dot_general=partial(jax.lax.dot_general,
                                preferred_element_type=jnp.float32),
            kernel_init=nn.initializers.normal(0.02),
        )

    # ------------------------------------------------------------- interface
    def init(self, rngs, input_ids, train: bool = False):
        del train
        rng = rngs["params"] if isinstance(rngs, dict) else rngs
        r_embed, r_blocks, r_norm, r_head = jax.random.split(rng, 4)
        _, S_len = input_ids.shape
        h_dummy = jnp.zeros((1, S_len, self.cfg.hidden_size), self.dtype)

        block_params = jax.vmap(
            lambda r: self.block.init(r, h_dummy)["params"]
        )(jax.random.split(r_blocks, self.cfg.num_layers))

        params = {
            "tok_embed": self.embed.init(r_embed, input_ids)["params"],
            "final_norm": self.final_norm.init(r_norm, h_dummy)["params"],
            "lm_head": self.lm_head.init(r_head, h_dummy)["params"],
        }
        if self.interleaved:
            # (L, ...) → (C, S, Lps, ...): entry (c, s) is virtual stage
            # v = c·S + s — the round-robin chunk assignment, stored so the
            # partition rules shard dim 1 over 'stage' (no runtime reshard).
            S = pipeline_lib.num_stages(self.mesh)
            C = self.chunks
            params["blocks_csl"] = jax.tree.map(
                lambda a: a.reshape((C, max(S, 1), -1) + a.shape[1:]),
                block_params,
            )
        else:
            params["blocks"] = block_params
        return {"params": params}

    def apply(self, variables, input_ids, train: bool = True, rngs=None,
              mutable=False):
        del train, rngs  # no dropout / batch stats in this recipe
        p = variables["params"]
        x = self.embed.apply({"params": p["tok_embed"]}, input_ids)
        x = x.astype(self.dtype)

        moe = self.moe is not None

        def block_apply(vars_, h):
            if moe:
                # MoE blocks sow load-balance/z losses; collect them here
                # and thread the scalar out of the pipeline's manual region.
                out, vs = self.block.apply(vars_, h, mutable=["losses"])
                aux = sum(
                    (jnp.sum(leaf) for leaf in
                     jax.tree_util.tree_leaves(vs.get("losses", {}))),
                    start=jnp.float32(0.0),
                )
                return out, aux
            return self.block.apply(vars_, h), jnp.float32(0.0)

        if self.cfg.remat:
            from pytorch_distributed_train_tpu.models.remat import POLICIES

            policy = getattr(self.cfg, "remat_policy", "full")
            if policy not in POLICIES:
                raise ValueError(
                    f"remat_policy must be one of {sorted(POLICIES)}, "
                    f"got {policy!r}")
            block_apply = jax.checkpoint(block_apply, policy=POLICIES[policy])

        def stage_fn(blocks_local, h):
            # blocks_local leaves: (layers_per_stage, ...) — scan applies
            # this stage's blocks in stacked order.
            def body(carry, p_one):
                h, aux = carry
                h, a = block_apply({"params": p_one}, h)
                return (h, aux + a), None

            (h, aux), _ = jax.lax.scan(body, (h, jnp.float32(0.0)),
                                       blocks_local)
            return h, aux

        x_mb = pipeline_lib.microbatch(x, self.num_microbatches)
        if self.interleaved:
            h_mb, aux = pipeline_lib.spmd_pipeline_interleaved(
                stage_fn, p["blocks_csl"], x_mb,
                mesh=self.mesh, with_aux=True,
            )
        else:
            h_mb, aux = pipeline_lib.spmd_pipeline(
                stage_fn, p["blocks"], x_mb,
                mesh=self.mesh, schedule=self.schedule, with_aux=True,
            )
        h = pipeline_lib.unmicrobatch(h_mb)

        h = self.final_norm.apply({"params": p["final_norm"]}, h)
        logits = self.lm_head.apply({"params": p["lm_head"]}, h)
        logits = logits.astype(jnp.float32)
        # Honor the flax mutable contract (steps.apply_model passes a list
        # of collections in train mode and expects an (out, vars) tuple);
        # the pipeline's aux total rides out through the losses collection.
        if mutable:
            losses = {"losses": {"moe_aux": aux}} if moe else {}
            return logits, losses
        return logits


def llama_pp(cfg, dtype, param_dtype, *, mesh, cp=None) -> PipelinedLlama:
    if getattr(cfg, "segment_eos_id", -1) >= 0:
        raise ValueError(
            "segment_eos_id (packed-document isolation) is not supported "
            "by the pipelined llama; use name='llama' for packed runs")
    return PipelinedLlama(
        cfg, dtype, param_dtype, mesh=mesh, cp=cp,
        num_microbatches=cfg.pipeline_microbatches,
        schedule=cfg.pipeline_schedule,
        chunks=cfg.pipeline_chunks,
    )
