"""Model registry: config name → Flax module (SURVEY H3, §7.2 `models/`).

The reference selects its model from config ("ResNet/ViT ... behind the same
config and checkpoint interface", BASELINE.json:5); this is the same switch,
plus the BERT/Llama rows of the acceptance matrix.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp

_REGISTRY: dict[str, Callable] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn
    return deco


def _populate():
    if _REGISTRY:
        return
    from pytorch_distributed_train_tpu.models import bert, llama, resnet, vit

    from pytorch_distributed_train_tpu.models import gpt2 as gpt2_mod

    _REGISTRY.update(
        {
            "resnet18": resnet.resnet18,
            "resnet50": resnet.resnet50,
            "vit_b16": vit.vit_b16,
            "bert_base": bert.bert_base,
            "llama": llama.llama,
            "gpt2": gpt2_mod.gpt2,
        }
    )
    from pytorch_distributed_train_tpu.models import pipeline_lm, t5

    _REGISTRY["llama_pp"] = pipeline_lm.llama_pp
    _REGISTRY["t5"] = t5.t5


def list_models() -> list[str]:
    _populate()
    return sorted(_REGISTRY)


def build_model(model_cfg, precision_cfg, mesh=None, mesh_cfg=None):
    """Build the Flax module for a ModelConfig under a PrecisionConfig.

    ``mesh`` + ``mesh_cfg`` activate context parallelism: when the mesh's
    context axis is >1 the transformer models route attention through
    ring/Ulysses (SURVEY §5.7) and constrain activations seq-sharded.
    """
    _populate()
    name = model_cfg.name
    if name not in _REGISTRY:
        raise KeyError(f"unknown model {name!r}; have {list_models()}")
    # ModelConfig.attention_impl is threaded into the modules as a static
    # attr (attn_impl) by each model ctor — no process-global state, so two
    # models with different backends coexist in one process.
    dtype = jnp.dtype(precision_cfg.compute_dtype)
    param_dtype = jnp.dtype(precision_cfg.param_dtype)
    cp = None
    if mesh is not None and mesh_cfg is not None and mesh.shape.get("context", 1) > 1:
        from pytorch_distributed_train_tpu.ops.attention import (
            ContextParallelConfig,
        )

        cp = ContextParallelConfig(
            mesh=mesh,
            impl=mesh_cfg.context_impl,
            layout=mesh_cfg.context_layout,
            batch_axes=tuple(mesh_cfg.batch_axes),
        )
    if name == "llama_pp":
        if mesh is None:
            raise ValueError("model 'llama_pp' needs a mesh (stage axis)")
        return _REGISTRY[name](model_cfg, dtype, param_dtype, cp=cp, mesh=mesh)
    if name.startswith(("llama", "bert", "gpt")):
        from pytorch_distributed_train_tpu.parallel.mesh import (
            activation_sharding_for,
        )

        act = activation_sharding_for(mesh, mesh_cfg)
        return _REGISTRY[name](model_cfg, dtype, param_dtype, cp=cp, act=act)
    return _REGISTRY[name](model_cfg, dtype, param_dtype, cp=cp)


def is_language_model(name: str) -> bool:
    return name.startswith(("bert", "llama", "gpt", "t5"))
