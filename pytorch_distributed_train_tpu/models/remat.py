"""Rematerialization policy selection (the torch activation-checkpointing
`checkpoint_impl`/selective-checkpoint analogue, config-driven).

``remat=True`` recomputes everything inside each transformer block during
backward (jax default policy). On large models the MXU-bound matmul
recompute can dominate backward time; ``remat_policy="dots"`` keeps matmul
outputs resident (XLA's ``dots_saveable``) and recomputes only the cheap
elementwise/norm chains — the classic flops↔HBM dial. "dots_no_batch"
saves only non-batch-dim matmuls (scales better with batch).
"""

from __future__ import annotations

import jax
import flax.linen as nn

from pytorch_distributed_train_tpu.ops.fused_update import (
    FUSED_EPILOGUE_NAME,
)

POLICIES = {
    "full": None,  # save nothing — recompute the whole block (default)
    "dots": jax.checkpoint_policies.dots_saveable,
    "dots_no_batch": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    # Audit-driven epilogue dial (ISSUE 14; ops/fused_update.py): save
    # every intermediate EXCEPT the outputs tagged "fused_epilogue"
    # (bias+GELU, residual+LayerNorm — model.fused_epilogues). The
    # expensive MXU work stays resident; only the cheap elementwise
    # epilogues recompute in backward — the inverse trade of "dots",
    # aimed at the elementwise rows of `perf_ledger --audit`. Remat
    # choices stay orthogonal to the fusion itself: any policy runs
    # over fused or unfused blocks.
    "no_fused_epilogue": jax.checkpoint_policies.
    save_anything_except_these_names(FUSED_EPILOGUE_NAME),
}


def remat_block(block_cls, enabled: bool, policy: str = "full"):
    """Wrap a block class with nn.remat per the configured policy."""
    if not enabled:
        return block_cls
    if policy not in POLICIES:
        raise ValueError(
            f"remat_policy must be one of {sorted(POLICIES)}, got {policy!r}")
    chosen = POLICIES[policy]
    if chosen is None:
        return nn.remat(block_cls)
    return nn.remat(block_cls, policy=chosen)
