"""Llama-2 decoder for pretraining (SURVEY H3; BASELINE.json:11).

Config 5 of the acceptance matrix: "Llama-2 7B pretrain, FSDP → XLA GSPMD
param sharding". Architecture: RMSNorm (pre-norm), rotary position
embeddings, GQA-capable attention, SwiGLU MLP, untied LM head — the Llama-2
recipe, sized by ModelConfig (7B = hidden 4096 / 32 layers / 32 heads /
mlp 11008 / vocab 32000).

TPU-first notes:
- Param layout is chosen for the FSDP×TP partition rules in
  parallel/partition.py::llama_rules (projection kernels keep hidden first so
  'fsdp' shards the big dim, 'tensor' the head dim).
- RoPE is precomputed per call at trace time — it folds into constants under
  jit; no cache buffers to shard.
- `remat=True` (the 7B preset default) checkpoints each block: standard
  HBM-for-FLOPs trade (SURVEY "jax.checkpoint / rematerialisation").
- Causal masking happens inside the attention core; no materialised (S,S)
  mask tensor at the model level.
"""

from __future__ import annotations

from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp

from pytorch_distributed_train_tpu.ops.attention import (
    ContextParallelConfig,
    dot_product_attention,
)


class RMSNorm(nn.Module):
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        dtype = x.dtype
        x = x.astype(jnp.float32)
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        y = x * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + self.eps)
        return (y * scale).astype(dtype)


def rope_frequencies(head_dim: int, max_seq_len: int, theta: float,
                     scaling: float = 1.0,
                     scaling_type: str = "linear") -> tuple:
    """Precompute cos/sin tables (S, head_dim/2) in fp32.

    ``scaling`` > 1 stretches the usable context to scaling x the
    pretrain length, two recipes (HF rope_scaling types):
    - "linear" (Chen et al. 2023): positions divide by the factor —
      rope(t, scaling=k) == rope(t/k) exactly; uniform compression.
    - "ntk" (NTK-aware, bloc97 2023 / HF "dynamic" at fixed factor):
      the BASE rescales (theta' = theta * k^(D/(D-2))) so the lowest
      frequencies stretch ~k x while the highest (local-order
      resolution) stay nearly untouched — often usable without any
      fine-tuning, unlike linear."""
    if scaling_type not in ("linear", "ntk"):
        raise ValueError(
            f"rope_scaling_type must be 'linear' or 'ntk', got "
            f"{scaling_type!r}")
    if scaling_type == "ntk" and scaling != 1.0:
        theta = theta * scaling ** (head_dim / (head_dim - 2))
        scaling = 1.0  # positions stay integral; the base does the work
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq_len, dtype=jnp.float32) / scaling
    freqs = jnp.outer(t, inv_freq)  # (S, D/2)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """x: (B, S, H, D). Rotates pairs (x[..., :D/2], x[..., D/2:]) — the
    'split-half' convention (matches HF Llama, so checkpoints interop)."""
    B, S, H, D = x.shape
    x1, x2 = x[..., : D // 2], x[..., D // 2:]
    cos = cos[None, :S, None, :].astype(x.dtype)
    sin = sin[None, :S, None, :].astype(x.dtype)
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


def packed_segments(input_ids: jnp.ndarray, eos_id: int):
    """Document structure of an EOS-packed block, derived at trace time.

    Packed LM blocks (data/text.py: docs joined by EOS, cut to seq_len)
    otherwise let attention leak across document boundaries. Returns
    (segments (B, S) int32 — the 1-based document id of every token (the
    EOS belongs to the document it ends); attention restricts to equal
    ids (ops/attention.py ``segments=``, which builds masks tile-by-tile
    on the chunked path instead of materialising (B, 1, S, S)) — and
    positions (B, S) int32 — each token's offset WITHIN its document, so
    rope/wpe treat every document as starting at position 0, exactly as
    if it were alone in the batch)."""
    B, S = input_ids.shape
    is_eos = input_ids == eos_id
    # token t starts a new segment iff t == 0 or token t-1 was EOS
    is_start = jnp.concatenate(
        [jnp.ones((B, 1), bool), is_eos[:, :-1]], axis=1)
    seg = jnp.cumsum(is_start.astype(jnp.int32), axis=1)  # (B, S), 1-based
    t = jnp.arange(S, dtype=jnp.int32)[None, :]
    starts = jax.lax.cummax(jnp.where(is_start, t, 0), axis=1)
    return seg, t - starts


def apply_rope_rows(x: jnp.ndarray, cos: jnp.ndarray,
                    sin: jnp.ndarray) -> jnp.ndarray:
    """Per-row-position rope: x (B, S, H, D), cos/sin (B, S, D/2) — each
    batch row carries its own position slice (continuous-batching decode,
    serving.py, where slots sit at different sequence offsets)."""
    D = x.shape[-1]
    x1, x2 = x[..., : D // 2], x[..., D // 2:]
    cos = cos[:, :, None, :].astype(x.dtype)
    sin = sin[:, :, None, :].astype(x.dtype)
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


KV_CACHE_DTYPES = ("", "bfloat16", "float16", "float8_e4m3fn",
                   "float8_e5m2")


def resolve_kv_dtype(kv_cache_dtype: str, default):
    """Validate + resolve the KV-cache storage dtype — ONE rule for every
    model family, erroring with the config key and allowed values instead
    of a numpy dtype error buried in a jit trace."""
    if kv_cache_dtype not in KV_CACHE_DTYPES:
        raise ValueError(
            f"model.kv_cache_dtype must be one of {KV_CACHE_DTYPES}, "
            f"got {kv_cache_dtype!r}")
    return jnp.dtype(kv_cache_dtype) if kv_cache_dtype else default


class LlamaAttention(nn.Module):
    num_heads: int
    num_kv_heads: int
    rope_theta: float
    rope_scaling: float
    max_seq_len: int
    dtype: jnp.dtype
    param_dtype: jnp.dtype
    rope_scaling_type: str = "linear"  # linear | ntk (rope_frequencies)
    cp: ContextParallelConfig | None = None
    attn_impl: str = "auto"  # threaded from ModelConfig.attention_impl
    window: int = 0  # sliding-window attention (0 = full causal)
    quant: str = ""  # "" | "int8" — AQT QAT matmuls (quant.int8_dot_general)
    # KV-cache STORAGE dtype ("" = compute dtype). "float8_e4m3fn" halves
    # cache HBM (and the per-step cache read — decode's bandwidth bill)
    # with a cast at write and read; no scales to manage (the fp8 KV
    # recipe production servers use; e4m3's ±448 range covers rope'd
    # K/V activations). Train-path attention is untouched.
    kv_cache_dtype: str = ""
    # Autoregressive decode: maintain a (B, max_seq_len, H_kv, D) KV cache in
    # the flax 'cache' collection (the idiomatic flax decode pattern — torch
    # analogue: HF past_key_values). Works for both the prefill call (S>1 at
    # offset 0) and single-token steps (S=1 at the running offset).
    decode: bool = False
    # Force the continuation path even for S>1 calls: tokens append at the
    # running cache offset instead of restarting at 0 (speculative
    # decoding's k+1-token verify pass, speculative.py).
    decode_multi: bool = False
    # Continuous batching (serving.py): cache_index is (B,) — every batch
    # row decodes at ITS OWN sequence offset, so serving slots at different
    # positions share one batched step. Prefill still starts rows at 0.
    decode_rows: bool = False
    # PAGED KV cache (serving.PagedContinuousBatcher — the vLLM
    # PagedAttention role, TPU-shaped): K/V live in a FLAT pool of
    # ``paged_blocks`` fixed-size blocks of ``page_size`` tokens,
    # (paged_blocks * page_size, H_kv, D) per layer, and each row maps
    # logical block j -> physical block via the (B, max_blocks)
    # ``block_tables`` argument (host-managed; sentinel ``paged_blocks``
    # marks unallocated entries, whose writes DROP and reads FILL zero —
    # out-of-bounds semantics do the masking, no branches). Resident KV
    # scales with actual sequence lengths instead of B x max_seq_len
    # worst-case rows. decode_rows-only (serving prefills on a dense B=1
    # row model and scatters the range into blocks).
    paged: bool = False
    page_size: int = 0
    paged_blocks: int = 0

    @nn.compact
    def __call__(self, x, segments=None, positions=None,
                 block_tables=None):
        B, S, C = x.shape
        head_dim = C // self.num_heads
        from pytorch_distributed_train_tpu.quant import quant_dot_general

        dg = quant_dot_general(self.quant)
        proj = lambda heads, name: nn.DenseGeneral(  # noqa: E731
            (heads, head_dim), axis=-1, use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype, dot_general=dg,
            kernel_init=nn.initializers.normal(0.02), name=name,
        )
        q = proj(self.num_heads, "q_proj")(x)
        k = proj(self.num_kv_heads, "k_proj")(x)
        v = proj(self.num_kv_heads, "v_proj")(x)

        if self.decode and self.paged:
            # Paged KV: flat per-layer pools + host block tables. Only
            # the decode_rows step/continuation shapes exist here —
            # serving prefills on a dense B=1 row model and scatters
            # the range into blocks (serving._paged_scatter_row_range).
            if not self.decode_rows:
                raise ValueError(
                    "paged KV cache requires decode_rows (continuous "
                    "batching); dense decode has no block tables")
            nb, bs = self.paged_blocks, self.page_size
            if nb < 1 or bs < 1:
                raise ValueError(
                    f"paged=True needs page_size >= 1 and paged_blocks "
                    f">= 1, got {bs}, {nb}")
            mb = -(-self.max_seq_len // bs)  # logical blocks per row
            Lp = mb * bs
            cdt = resolve_kv_dtype(self.kv_cache_dtype, k.dtype)
            p_k = self.variable("cache", "pool_key", jnp.zeros,
                                (nb * bs, self.num_kv_heads, head_dim),
                                cdt)
            p_v = self.variable("cache", "pool_value", jnp.zeros,
                                (nb * bs, self.num_kv_heads, head_dim),
                                cdt)
            c_i = self.variable("cache", "cache_index",
                                lambda: jnp.zeros((B,), jnp.int32))
            if S > 1 and not self.decode_multi:
                raise ValueError(
                    "paged prefill is unsupported: prefill on the dense "
                    "row model and scatter the range into blocks")
            tables = (block_tables if block_tables is not None
                      else jnp.full((B, mb), nb, jnp.int32))  # init trace
            idx = c_i.value  # (B,)
            cos, sin = rope_frequencies(head_dim, self.max_seq_len,
                                        self.rope_theta,
                                        self.rope_scaling,
                                        self.rope_scaling_type)
            take = lambda tbl, i: jax.lax.dynamic_slice_in_dim(  # noqa: E731
                tbl, i, S, 0)
            q = apply_rope_rows(q, jax.vmap(take, (None, 0))(cos, idx),
                                jax.vmap(take, (None, 0))(sin, idx))
            k = apply_rope_rows(k, jax.vmap(take, (None, 0))(cos, idx),
                                jax.vmap(take, (None, 0))(sin, idx))
            # Scatter the S new tokens through the block map. Logical
            # block indices clip into the table (gather default);
            # unallocated/dead entries hold the sentinel ``nb`` so their
            # physical index lands out of bounds and the write DROPS —
            # free-running dead rows and re-pinned parked rows stay
            # harmless with zero host branching, the same discipline as
            # the dense cache's masked garbage writes.
            pos = idx[:, None] + jnp.arange(S)  # (B, S)
            # Clamp the FLAT position (the dense path's clamp-to-end
            # discipline): a parked row's free-running index must pile
            # its garbage writes on the single final position Lp-1 —
            # clamping block and offset separately would instead cycle
            # writes through the whole last block, corrupting a parked
            # session's real tail content over time. Lp-1 is always
            # masked (k_pos <= q_pos < L <= Lp never reaches it before
            # a real write does).
            pos_w = jnp.clip(pos, 0, Lp - 1)
            pb = jnp.take_along_axis(tables, pos_w // bs, axis=1)
            phys = pb * bs + pos_w % bs  # (B, S); >= nb*bs if unallocated
            kv_shape = (B * S, self.num_kv_heads, head_dim)
            p_k.value = p_k.value.at[phys.reshape(-1)].set(
                k.astype(cdt).reshape(kv_shape), mode="drop")
            p_v.value = p_v.value.at[phys.reshape(-1)].set(
                v.astype(cdt).reshape(kv_shape), mode="drop")
            c_i.value = idx + S
            # Gather each row's logical view (B, Lp) out of the pool —
            # unallocated blocks read zeros (mode='fill'), and the
            # position mask hides everything past the row's offset
            # anyway. Transient: one (B, Lp, H_kv, D) buffer per layer
            # (freed across layers); RESIDENT KV is just the pool.
            jpos = jnp.arange(Lp)
            physg = (jnp.take(tables, jpos // bs, axis=1) * bs
                     + jpos % bs)  # (B, Lp)
            k_all = jnp.take(p_k.value, physg.reshape(-1), axis=0,
                             mode="fill", fill_value=0).reshape(
                                 B, Lp, self.num_kv_heads, head_dim)
            v_all = jnp.take(p_v.value, physg.reshape(-1), axis=0,
                             mode="fill", fill_value=0).reshape(
                                 B, Lp, self.num_kv_heads, head_dim)
            k_pos = jnp.arange(Lp)
            mask = k_pos[None, None, :] <= pos[:, :, None]  # (B, S, Lp)
            if self.window:
                mask &= (pos[:, :, None] - k_pos[None, None, :]
                         ) < self.window
            y = dot_product_attention(q, k_all.astype(self.dtype),
                                      v_all.astype(self.dtype),
                                      mask=mask[:, None], impl="xla")
        elif self.decode:
            L = self.max_seq_len
            cdt = resolve_kv_dtype(self.kv_cache_dtype, k.dtype)
            c_k = self.variable("cache", "cached_key", jnp.zeros,
                                (B, L, self.num_kv_heads, head_dim), cdt)
            c_v = self.variable("cache", "cached_value", jnp.zeros,
                                (B, L, self.num_kv_heads, head_dim), cdt)
            # decode_rows + decode_multi = MULTI-TOKEN rows continuation
            # (serving.py session resume ingests a whole user turn at each
            # row's offset); plain decode_rows steps are its S=1 case.
            idx_shape = (B,) if self.decode_rows else ()
            c_i = self.variable("cache", "cache_index",
                                lambda: jnp.zeros(idx_shape, jnp.int32))
            if S > 1 and not self.decode_multi:
                # Prefill: a multi-token decode call means "start this cache
                # from position 0" (generate.py's contract). Positions are
                # static, attention is plain causal over the PROMPT ONLY —
                # O(S^2), not O(S*L) over the padded cache — and the
                # configured attn_impl (incl. Pallas) still applies.
                cos, sin = rope_frequencies(head_dim, S, self.rope_theta,
                                             self.rope_scaling,
                                             self.rope_scaling_type)
                q = apply_rope(q, cos, sin)
                k = apply_rope(k, cos, sin)
                c_k.value = jax.lax.dynamic_update_slice_in_dim(
                    c_k.value, k.astype(cdt), 0, 1)
                c_v.value = jax.lax.dynamic_update_slice_in_dim(
                    c_v.value, v.astype(cdt), 0, 1)
                c_i.value = jnp.full(idx_shape, S, jnp.int32)
                y = dot_product_attention(q, k, v, causal=True,
                                          impl=self.attn_impl,
                                          window=self.window)
            elif self.decode_rows:
                # Per-row continuation: row b's S tokens append at ITS
                # offset idx[b]. vmap turns the per-row dynamic updates
                # into one scatter; positions/mask are per-row too.
                idx = c_i.value  # (B,)
                cos, sin = rope_frequencies(head_dim, L, self.rope_theta,
                                            self.rope_scaling,
                                            self.rope_scaling_type)
                take = lambda tbl, i: jax.lax.dynamic_slice_in_dim(  # noqa: E731
                    tbl, i, S, 0)
                cos_r = jax.vmap(take, (None, 0))(cos, idx)
                sin_r = jax.vmap(take, (None, 0))(sin, idx)
                q = apply_rope_rows(q, cos_r, sin_r)
                k = apply_rope_rows(k, cos_r, sin_r)
                upd = lambda c, new, i: jax.lax.dynamic_update_slice_in_dim(  # noqa: E731
                    c, new, i, 0)
                c_k.value = jax.vmap(upd)(c_k.value, k.astype(cdt), idx)
                c_v.value = jax.vmap(upd)(c_v.value, v.astype(cdt), idx)
                c_i.value = idx + S
                q_pos = idx[:, None] + jnp.arange(S)  # (B, S)
                k_pos = jnp.arange(L)
                mask = k_pos[None, None, :] <= q_pos[:, :, None]  # (B, S, L)
                if self.window:
                    mask &= (q_pos[:, :, None] - k_pos[None, None, :]
                             ) < self.window
                y = dot_product_attention(q, c_k.value.astype(self.dtype),
                                          c_v.value.astype(self.dtype),
                                          mask=mask[:, None], impl="xla")
            else:
                # Step(s) at the running offset (dynamic index). Handles
                # any static S: with decode_multi this is the multi-token
                # CONTINUATION path (speculative.py's verify pass appends
                # k+1 tokens mid-stream) — positions are idx..idx+S-1 and
                # the mask below is causal across the new tokens too.
                idx = c_i.value
                cos, sin = rope_frequencies(head_dim, L, self.rope_theta,
                                             self.rope_scaling,
                                             self.rope_scaling_type)
                cos = jax.lax.dynamic_slice_in_dim(cos, idx, S, 0)
                sin = jax.lax.dynamic_slice_in_dim(sin, idx, S, 0)
                q = apply_rope(q, cos, sin)
                k = apply_rope(k, cos, sin)
                c_k.value = jax.lax.dynamic_update_slice_in_dim(
                    c_k.value, k.astype(cdt), idx, 1)
                c_v.value = jax.lax.dynamic_update_slice_in_dim(
                    c_v.value, v.astype(cdt), idx, 1)
                c_i.value = idx + S
                # mask against absolute positions; the unwritten cache tail
                # (> idx) is masked out so the static length leaks nothing
                q_pos = idx + jnp.arange(S)
                k_pos = jnp.arange(L)
                mask = k_pos[None, :] <= q_pos[:, None]
                if self.window:
                    mask &= (q_pos[:, None] - k_pos[None, :]) < self.window
                mask = mask[None, None]
                y = dot_product_attention(q, c_k.value.astype(self.dtype),
                                          c_v.value.astype(self.dtype),
                                          mask=mask, impl="xla")
        else:
            cos, sin = rope_frequencies(head_dim, S, self.rope_theta,
                                             self.rope_scaling,
                                             self.rope_scaling_type)
            if positions is not None:
                # packed segments: each document restarts at position 0
                q = apply_rope_rows(q, cos[positions], sin[positions])
                k = apply_rope_rows(k, cos[positions], sin[positions])
            else:
                q = apply_rope(q, cos, sin)
                k = apply_rope(k, cos, sin)

            y = dot_product_attention(q, k, v, causal=True, cp=self.cp,
                                      impl=self.attn_impl,
                                      window=self.window, segments=segments)
        y = nn.DenseGeneral(
            C, axis=(-2, -1), use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype, dot_general=dg,
            kernel_init=nn.initializers.normal(0.02), name="o_proj",
        )(y)
        return y


class LlamaMLP(nn.Module):
    mlp_dim: int
    dtype: jnp.dtype
    param_dtype: jnp.dtype
    quant: str = ""  # "" | "int8" (MoE experts always pass "" — fp experts)

    @nn.compact
    def __call__(self, x):
        from pytorch_distributed_train_tpu.quant import quant_dot_general

        dg = quant_dot_general(self.quant)
        dense = lambda dim, name: nn.Dense(  # noqa: E731
            dim, use_bias=False, dtype=self.dtype, param_dtype=self.param_dtype,
            dot_general=dg,
            kernel_init=nn.initializers.normal(0.02), name=name,
        )
        gate = nn.silu(dense(self.mlp_dim, "gate_proj")(x))
        up = dense(self.mlp_dim, "up_proj")(x)
        return dense(x.shape[-1], "down_proj")(gate * up)


class LlamaBlock(nn.Module):
    num_heads: int
    num_kv_heads: int
    mlp_dim: int
    rope_theta: float
    rope_scaling: float
    max_seq_len: int
    rms_norm_eps: float
    dtype: jnp.dtype
    param_dtype: jnp.dtype
    rope_scaling_type: str = "linear"
    cp: ContextParallelConfig | None = None
    moe: "MoeSpec | None" = None  # set → MoE FFN instead of dense (ops/moe.py)
    attn_impl: str = "auto"
    window: int = 0
    quant: str = ""
    kv_cache_dtype: str = ""
    decode: bool = False
    decode_multi: bool = False
    decode_rows: bool = False
    paged: bool = False
    page_size: int = 0
    paged_blocks: int = 0

    @nn.compact
    def __call__(self, x, segments=None, positions=None,
                 block_tables=None):
        h = RMSNorm(self.rms_norm_eps, name="input_norm")(x)
        x = x + LlamaAttention(
            self.num_heads, self.num_kv_heads, self.rope_theta,
            self.rope_scaling, self.max_seq_len, self.dtype,
            self.param_dtype, rope_scaling_type=self.rope_scaling_type,
            cp=self.cp, attn_impl=self.attn_impl,
            window=self.window, quant=self.quant,
            kv_cache_dtype=self.kv_cache_dtype, decode=self.decode,
            decode_multi=self.decode_multi, decode_rows=self.decode_rows,
            paged=self.paged, page_size=self.page_size,
            paged_blocks=self.paged_blocks,
            name="attn",
        )(h, segments=segments, positions=positions,
          block_tables=block_tables)
        h = RMSNorm(self.rms_norm_eps, name="post_attn_norm")(x)
        if self.moe is not None:
            from pytorch_distributed_train_tpu.ops.moe import MoeMLP

            mlp = MoeMLP(self.moe, LlamaMLP, self.mlp_dim, self.dtype,
                         self.param_dtype, name="moe_mlp")
        else:
            mlp = LlamaMLP(self.mlp_dim, self.dtype, self.param_dtype,
                           quant=self.quant, name="mlp")
        x = x + mlp(h)
        return x


class LlamaForCausalLM(nn.Module):
    """Input: input_ids (B, S). Output: (B, S, vocab) fp32 logits."""

    vocab_size: int
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    mlp_dim: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    # Position-interpolation factor: serve/fine-tune at rope_scaling x
    # the pretrain context, by "linear" (positions divide) or "ntk"
    # (base rescales; often usable without fine-tuning) recipe.
    rope_scaling: float = 1.0
    rope_scaling_type: str = "linear"
    rms_norm_eps: float = 1e-5
    remat: bool = True
    remat_policy: str = "full"  # full | dots | dots_no_batch (models/remat.py)
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    cp: ContextParallelConfig | None = None
    moe: "MoeSpec | None" = None
    attn_impl: str = "auto"
    # AQT-style int8 QAT ("" | "int8"): attention + MLP matmuls run
    # int8xint8->int32 on the MXU with dynamic absmax scales and a
    # straight-through backward (quant.int8_dot_general). The lm_head and
    # MoE experts stay in the compute dtype.
    quant_training: str = ""
    # Sliding-window attention span (Mistral recipe; 0 = full causal).
    attention_window: int = 0
    # Packed-block document isolation (packed_segments): >= 0 names the
    # EOS id delimiting documents; attention masks across documents and
    # positions restart per document. -1 = off (documents attend across
    # pack boundaries, the simple-packing default).
    segment_eos_id: int = -1
    decode: bool = False  # KV-cache autoregressive mode (generate.py)
    kv_cache_dtype: str = ""  # "" | fp8 dtypes — cache STORAGE dtype
    # Multi-token continuation in decode mode (speculative.py verify pass)
    decode_multi: bool = False
    # Per-row cache offsets for continuous-batching serving (serving.py)
    decode_rows: bool = False
    # Paged KV pool (serving.PagedContinuousBatcher): block-granular
    # cache residency with host block tables (see LlamaAttention.paged)
    paged: bool = False
    page_size: int = 0
    paged_blocks: int = 0
    # Fused chunked head+CE (losses.chunked_causal_ce): __call__ returns
    # {'loss_sum','weight_sum'} instead of logits — (B,S,V) fp32 logits
    # never materialize. Pair with loss="fused_causal_lm_xent".
    fused_loss: bool = False
    # SP/CP activation anchoring (parallel/mesh.py ActivationSharding):
    # keeps norms/residuals seq-sharded between attention / TP-matmul
    # regions — CP without it replicates seq outside the shard_map regions;
    # SP (Megatron SequenceParallel) IS this constraint.
    act: "object | None" = None

    @nn.compact
    def __call__(self, input_ids, train: bool = True, loss_mask=None,
                 block_tables=None):
        del train  # no dropout in the Llama-2 pretrain recipe
        segments = positions = None
        if self.segment_eos_id >= 0:
            if self.decode:
                raise ValueError(
                    "segment_eos_id is a packed-TRAINING feature; decode "
                    "serves one unpacked sequence per row")
            if self.cp is not None and self.cp.active:
                raise ValueError(
                    "segment_eos_id with context parallelism is not "
                    "supported (the segment mask spans the full sequence); "
                    "use context=1 for packed-isolation runs")
            segments, positions = packed_segments(input_ids,
                                                   self.segment_eos_id)
        x = nn.Embed(
            self.vocab_size, self.hidden_size,
            embedding_init=nn.initializers.normal(0.02),
            param_dtype=self.param_dtype, name="tok_embed",
        )(input_ids).astype(self.dtype)
        if self.act is not None:
            x = self.act.constrain(x)

        from pytorch_distributed_train_tpu.models.remat import remat_block

        block_cls = remat_block(LlamaBlock, self.remat, self.remat_policy)
        for i in range(self.num_layers):
            moe = (self.moe if self.moe is not None
                   and self.moe.active_for_layer(i) else None)
            x = block_cls(
                self.num_heads, self.num_kv_heads, self.mlp_dim,
                self.rope_theta, self.rope_scaling, self.max_seq_len,
                self.rms_norm_eps, self.dtype, self.param_dtype,
                rope_scaling_type=self.rope_scaling_type,
                cp=self.cp, moe=moe,
                attn_impl=self.attn_impl, window=self.attention_window,
                quant=self.quant_training,
                kv_cache_dtype=self.kv_cache_dtype, decode=self.decode,
                decode_multi=self.decode_multi, decode_rows=self.decode_rows,
                paged=self.paged, page_size=self.page_size,
                paged_blocks=self.paged_blocks,
                name=f"layer{i}",
            )(x, segments=segments, positions=positions,
              block_tables=block_tables)
            if self.act is not None:
                x = self.act.constrain(x)

        x = RMSNorm(self.rms_norm_eps, name="final_norm")(x)
        # Head matmul in the compute dtype with fp32 accumulation: bf16
        # operands hit the MXU at full rate while preferred_element_type
        # keeps the (B,S,V) logits fp32 without an intermediate bf16
        # rounding (an fp32xfp32 matmul here ran at a fraction of MXU rate
        # and the head is ~1/6 of total model FLOPs at 32k vocab).
        head = nn.Dense(
            self.vocab_size, use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype,
            dot_general=partial(jax.lax.dot_general,
                                preferred_element_type=jnp.float32),
            kernel_init=nn.initializers.normal(0.02), name="lm_head",
        )
        if self.fused_loss and not self.decode:
            from pytorch_distributed_train_tpu.losses import chunked_causal_ce

            # Create the head params at the standard path without the full
            # matmul (the tiny call is dead code XLA eliminates), then hand
            # the kernel ARRAY to the pure chunked-CE helper — a flax
            # submodule can't be called inside jax.checkpoint, an array can.
            _ = head(x[:, :1])
            kernel = jnp.asarray(head.variables["params"]["kernel"],
                                 self.dtype)
            return chunked_causal_ce(x, kernel, input_ids,
                                     loss_mask=loss_mask)
        logits = head(x)
        return logits.astype(jnp.float32)


def llama(cfg, dtype, param_dtype, cp=None, act=None) -> LlamaForCausalLM:
    resolve_kv_dtype(getattr(cfg, "kv_cache_dtype", ""), dtype)  # validate NOW
    moe = None
    if getattr(cfg, "num_experts", 0) > 1:
        from pytorch_distributed_train_tpu.ops.moe import MoeSpec

        moe = MoeSpec(
            num_experts=cfg.num_experts,
            top_k=cfg.expert_top_k,
            capacity_factor=cfg.expert_capacity_factor,
            aux_weight=cfg.moe_aux_weight,
            zloss_weight=cfg.moe_zloss_weight,
            every=cfg.moe_every,
            router=cfg.moe_router,
        )
    return LlamaForCausalLM(
        cp=cp,
        moe=moe,
        act=act,
        quant_training=getattr(cfg, "quant_training", ""),
        attn_impl=getattr(cfg, "attention_impl", "auto"),
        attention_window=getattr(cfg, "attention_window", 0),
        kv_cache_dtype=getattr(cfg, "kv_cache_dtype", ""),
        segment_eos_id=getattr(cfg, "segment_eos_id", -1),
        fused_loss=getattr(cfg, "fused_lm_loss", False),
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.hidden_size,
        num_layers=cfg.num_layers,
        num_heads=cfg.num_heads,
        num_kv_heads=cfg.num_kv_heads or cfg.num_heads,
        mlp_dim=cfg.mlp_dim,
        max_seq_len=cfg.max_seq_len,
        rope_theta=cfg.rope_theta,
        rope_scaling=getattr(cfg, "rope_scaling", 1.0),
        rope_scaling_type=getattr(cfg, "rope_scaling_type", "linear"),
        rms_norm_eps=cfg.rms_norm_eps,
        remat=cfg.remat,
        remat_policy=getattr(cfg, "remat_policy", "full"),
        dtype=dtype,
        param_dtype=param_dtype,
    )
