"""BERT-base for masked-LM pretraining (SURVEY H3; BASELINE.json:10).

The reference's config 4 is "BERT-base MLM on Wikipedia (sequence model, LAMB
optimizer)". This is the classic post-LN BERT encoder: learned word +
position + segment embeddings, 12 post-LN blocks, tied-embedding MLM head
with GELU transform. Attention rides ops.attention (BSHD, fp32 softmax).

TPU notes:
- Padding mask arrives as (B, S) int/bool; expanded once to (B,1,1,S) —
  static shapes, no data-dependent control flow (XLA requirement).
- MLM loss is computed over ALL positions with a weight mask rather than
  gathering masked positions (dynamic-size gather would break static shapes);
  see losses.mlm_xent.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from pytorch_distributed_train_tpu.ops.attention import (
    ContextParallelConfig,
    dot_product_attention,
)


class BertSelfAttention(nn.Module):
    num_heads: int
    dropout_rate: float
    dtype: jnp.dtype
    param_dtype: jnp.dtype
    # CP on BERT requires context_impl='ulysses' (pad masks don't rotate
    # around a ring — ops.attention dispatch enforces this).
    cp: ContextParallelConfig | None = None
    attn_impl: str = "auto"  # threaded from ModelConfig.attention_impl

    @nn.compact
    def __call__(self, x, pad_mask, deterministic: bool):
        B, S, C = x.shape
        head_dim = C // self.num_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (self.num_heads, head_dim), axis=-1, dtype=self.dtype,
            param_dtype=self.param_dtype, name=name,
        )
        q, k, v = dense("query")(x), dense("key")(x), dense("value")(x)
        y = dot_product_attention(q, k, v, mask=pad_mask, cp=self.cp,
                                  impl=self.attn_impl)
        y = nn.DenseGeneral(
            C, axis=(-2, -1), dtype=self.dtype, param_dtype=self.param_dtype,
            name="attn_out",
        )(y)
        y = nn.Dropout(self.dropout_rate)(y, deterministic=deterministic)
        return y


class BertLayer(nn.Module):
    """Post-LN transformer block (original BERT ordering)."""

    num_heads: int
    mlp_dim: int
    dropout_rate: float
    deterministic: bool
    dtype: jnp.dtype
    param_dtype: jnp.dtype
    cp: ContextParallelConfig | None = None
    attn_impl: str = "auto"
    fused_epilogues: bool = False

    @nn.compact
    def __call__(self, x, pad_mask):
        # Audit-driven fused epilogues (ops/fused_update.py;
        # model.fused_epilogues): the post-LN block's two residual-add+
        # LayerNorm chains and the MLP's bias+GELU chain become single
        # tagged expressions — param names/shapes and numerics identical
        # to the plain formulation (pinned by tests), the tag feeds the
        # "no_fused_epilogue" remat policy.
        if self.fused_epilogues:
            from pytorch_distributed_train_tpu.ops.fused_update import (
                FusedDenseGelu,
                FusedResidualLayerNorm,
            )

            res_ln = lambda name: FusedResidualLayerNorm(  # noqa: E731
                epsilon=1e-12, param_dtype=jnp.float32, name=name)
            attn = BertSelfAttention(
                self.num_heads, self.dropout_rate, self.dtype,
                self.param_dtype, cp=self.cp, attn_impl=self.attn_impl,
                name="attn",
            )(x, pad_mask, self.deterministic)
            x = res_ln("ln_attn")(attn, x).astype(self.dtype)
            h = FusedDenseGelu(self.mlp_dim, dtype=self.dtype,
                               param_dtype=self.param_dtype,
                               name="mlp_in")(x)
            h = nn.Dense(x.shape[-1], dtype=self.dtype,
                         param_dtype=self.param_dtype, name="mlp_out")(h)
            h = nn.Dropout(self.dropout_rate)(
                h, deterministic=self.deterministic)
            x = res_ln("ln_mlp")(h, x).astype(self.dtype)
            return x
        ln = lambda name: nn.LayerNorm(  # noqa: E731
            epsilon=1e-12, dtype=jnp.float32, param_dtype=jnp.float32, name=name
        )
        attn = BertSelfAttention(
            self.num_heads, self.dropout_rate, self.dtype, self.param_dtype,
            cp=self.cp, attn_impl=self.attn_impl, name="attn",
        )(x, pad_mask, self.deterministic)
        x = ln("ln_attn")(x + attn).astype(self.dtype)
        h = nn.Dense(self.mlp_dim, dtype=self.dtype, param_dtype=self.param_dtype,
                     name="mlp_in")(x)
        h = nn.gelu(h, approximate=False)  # exact erf GELU (BERT/HF convention)
        h = nn.Dense(x.shape[-1], dtype=self.dtype, param_dtype=self.param_dtype,
                     name="mlp_out")(h)
        h = nn.Dropout(self.dropout_rate)(h, deterministic=self.deterministic)
        x = ln("ln_mlp")(x + h).astype(self.dtype)
        return x


class BertForMLM(nn.Module):
    """Inputs: dict with input_ids (B,S), attention_mask (B,S), optional
    token_type_ids (B,S). Output: (B, S, vocab) fp32 logits."""

    vocab_size: int
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    max_seq_len: int = 512
    dropout_rate: float = 0.1
    remat: bool = False
    remat_policy: str = "full"  # full | dots | dots_no_batch (models/remat.py)
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    cp: ContextParallelConfig | None = None
    attn_impl: str = "auto"
    fused_epilogues: bool = False
    # SP/CP activation anchoring (parallel/mesh.py ActivationSharding)
    act: "object | None" = None

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 train: bool = True):
        deterministic = not train
        B, S = input_ids.shape

        word = nn.Embed(self.vocab_size, self.hidden_size,
                        embedding_init=nn.initializers.normal(0.02),
                        param_dtype=self.param_dtype, name="word_embed")
        x = word(input_ids)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, self.max_seq_len, self.hidden_size), self.param_dtype)
        x = x + pos[:, :S].astype(x.dtype)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = x + nn.Embed(2, self.hidden_size,
                         embedding_init=nn.initializers.normal(0.02),
                         param_dtype=self.param_dtype, name="type_embed")(token_type_ids)
        x = nn.LayerNorm(epsilon=1e-12, dtype=jnp.float32, param_dtype=jnp.float32,
                         name="embed_ln")(x)
        x = nn.Dropout(self.dropout_rate)(x, deterministic=deterministic)
        x = x.astype(self.dtype)
        if self.act is not None:
            x = self.act.constrain(x)

        if attention_mask is None:
            pad_mask = None
        else:
            pad_mask = attention_mask[:, None, None, :].astype(bool)  # (B,1,1,S)

        from pytorch_distributed_train_tpu.models.remat import remat_block

        block_cls = remat_block(BertLayer, self.remat, self.remat_policy)
        for i in range(self.num_layers):
            x = block_cls(
                self.num_heads, self.mlp_dim, self.dropout_rate, deterministic,
                self.dtype, self.param_dtype, cp=self.cp,
                attn_impl=self.attn_impl,
                fused_epilogues=self.fused_epilogues, name=f"layer{i}",
            )(x, pad_mask)
            if self.act is not None:
                x = self.act.constrain(x)

        # MLM head: dense + GELU + LN, then decode against tied word embeddings.
        if self.fused_epilogues:
            from pytorch_distributed_train_tpu.ops.fused_update import (
                FusedDenseGelu,
            )

            h = FusedDenseGelu(self.hidden_size, dtype=self.dtype,
                               param_dtype=self.param_dtype,
                               name="mlm_dense")(x)
        else:
            h = nn.Dense(self.hidden_size, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="mlm_dense")(x)
            h = nn.gelu(h, approximate=False)  # exact erf (BERT/HF convention)
        h = nn.LayerNorm(epsilon=1e-12, dtype=jnp.float32, param_dtype=jnp.float32,
                         name="mlm_ln")(h)
        # Tied-embedding decode in the compute dtype with fp32 accumulation:
        # bf16 operands run at full MXU rate; preferred_element_type keeps
        # the (B,S,V) logits fp32 (an fp32xfp32 matmul here is several times
        # slower on the MXU).
        emb = jnp.asarray(word.embedding, self.dtype)  # (V, C)
        logits = jax.lax.dot_general(
            h.astype(self.dtype), emb,
            (((h.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        logits = logits + self.param(
            "mlm_bias", nn.initializers.zeros, (self.vocab_size,), jnp.float32
        )
        return logits.astype(jnp.float32)


def bert_base(cfg, dtype, param_dtype, cp=None, act=None) -> BertForMLM:
    return BertForMLM(
        cp=cp,
        act=act,
        attn_impl=getattr(cfg, "attention_impl", "auto"),
        fused_epilogues=getattr(cfg, "fused_epilogues", False),
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.hidden_size,
        num_layers=cfg.num_layers,
        num_heads=cfg.num_heads,
        mlp_dim=cfg.mlp_dim,
        max_seq_len=cfg.max_seq_len,
        dropout_rate=cfg.dropout_rate,
        remat=cfg.remat,
        remat_policy=getattr(cfg, "remat_policy", "full"),
        dtype=dtype,
        param_dtype=param_dtype,
    )
