"""Model zoo behind one registry keyed by config (SURVEY H3).

The reference exposes ResNet/ViT "behind the same config and checkpoint
interface" (BASELINE.json:5); the acceptance matrix adds BERT-base and
Llama-2 7B (BASELINE.json:10-11). All models here are Flax Linen modules with
an explicit ``dtype``/``param_dtype`` policy replacing torch AMP autocast
(SURVEY C18).
"""

from pytorch_distributed_train_tpu.models.registry import build_model, list_models  # noqa: F401
