"""ResNet-18/50 in Flax Linen (SURVEY H3; BASELINE.json:7-8).

TPU-first choices, not a torchvision translation:
- NHWC layout throughout (XLA:TPU's native conv layout; NCHW forces
  transposes before every conv).
- BatchNorm statistics are always fp32 (flax promotes reductions to fp32 —
  `force_float32_reductions`), but BN *outputs* follow the compute dtype:
  emitting bf16 halves the HBM traffic of every BN→ReLU→conv chain, which
  profiling showed dominating step time when BN emitted fp32.
  Under GSPMD jit the batch dim is sharded, so flax's plain batch
  reduction compiles to a GLOBAL mean/var (XLA inserts the all-reduce) —
  i.e. SyncBatchNorm semantics by construction, with the collective placed
  by the compiler instead of torch's explicit process-group broadcast.
  torch DDP's *default* (local-batch statistics, SyncBN opt-in) has no
  cheap GSPMD analogue and normalises over fewer samples anyway; global
  stats are the strictly-more-correct behavior the reference opts into
  via SyncBatchNorm.
- A `cifar_stem` flag swaps the 7x7/s2+maxpool ImageNet stem for the 3x3/s1
  stem every CIFAR ResNet-18 recipe uses — the reference's config 1 vs 2
  distinction (BASELINE.json:7 vs :8).

Weight init mirrors the reference-era recipe: He-normal conv kernels,
zero-init for the final BN scale in each residual branch (the "zero-init
residual" trick), so early training matches torch defaults closely enough for
the golden-numerics cross-check (SURVEY §4.5).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

ModuleDef = Any


class SpaceToDepthStem(nn.Module):
    """MXU-friendly ImageNet stem: the 7x7/s2 conv over 3-channel input
    wastes the 128-wide systolic array (C_in=3); rewriting it as a 4x4/s1
    conv over a 2x2 space-to-depth input (C_in=12) is mathematically
    EXACT — the 7x7 kernel zero-pads to 8 taps and regroups into the s2d
    channel layout. The PARAMETER stays the canonical (7,7,3,F) kernel
    (same name/shape as the nn.Conv stem), so checkpoints and the torch
    interop bridge are unaffected; only the compute path changes. The
    MLPerf-era TPU ResNet recipe, in-graph instead of in-pipeline."""

    filters: int
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        f = self.filters
        kernel = self.param(
            "kernel",
            nn.initializers.variance_scaling(2.0, "fan_out", "normal"),
            (7, 7, 3, f), self.param_dtype,
        )
        # w8[0]=0 zero tap; w4[ry,rx,(dy,dx,ch)] = w8[2ry+dy, 2rx+dx, ch]
        w8 = jnp.pad(kernel, ((1, 0), (1, 0), (0, 0), (0, 0)))
        w4 = (w8.reshape(4, 2, 4, 2, 3, f)
              .transpose(0, 2, 1, 3, 4, 5)
              .reshape(4, 4, 12, f))
        # Left pad 4 (3 for the conv + 1 dead column under the zero tap),
        # right pad 2; then 2x2 space-to-depth with matching (dy,dx,ch)
        # channel packing.
        xp = jnp.pad(x, ((0, 0), (4, 2), (4, 2), (0, 0)))
        b, h, w, c = xp.shape
        xs = (xp.reshape(b, h // 2, 2, w // 2, 2, c)
              .transpose(0, 1, 3, 2, 4, 5)
              .reshape(b, h // 2, w // 2, 4 * c))
        return lax.conv_general_dilated(
            xs, w4.astype(self.dtype), (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )


class ResNetBlock(nn.Module):
    """Basic 3x3+3x3 block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: int = 1

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides), name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), name="conv2")(y)
        y = self.norm(scale_init=nn.initializers.zeros, name="bn2")(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), (self.strides, self.strides), name="conv_proj"
            )(residual)
            residual = self.norm(name="bn_proj")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    """1x1-3x3-1x1 bottleneck (ResNet-50/101/152)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: int = 1

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1), name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides), name="conv2")(y)
        y = self.norm(name="bn2")(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1), name="conv3")(y)
        y = self.norm(scale_init=nn.initializers.zeros, name="bn3")(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), (self.strides, self.strides), name="conv_proj"
            )(residual)
            residual = self.norm(name="bn_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """Input: NHWC images. Output: (batch, num_classes) logits in fp32."""

    stage_sizes: Sequence[int]
    block_cls: Callable
    num_classes: int
    num_filters: int = 64
    cifar_stem: bool = False
    stem: str = "conv"  # conv | space_to_depth (ImageNet stem only)
    # 0.0 turns each train-mode call's running stats into exactly THAT
    # batch's stats — the probe trainer.update_bn uses to re-estimate
    # statistics for averaged (SWA/EMA) weights, torch swa_utils
    # update_bn style
    bn_momentum: float = 0.9
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(
            nn.Conv,
            use_bias=False,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=nn.initializers.variance_scaling(2.0, "fan_out", "normal"),
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=self.bn_momentum,
            epsilon=1e-5,
            # stats are fp32 regardless (flax force_float32_reductions);
            # outputs follow the compute dtype to halve elementwise bandwidth
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )

        if self.stem not in ("conv", "space_to_depth"):
            # A typo'd --set model.stem would otherwise silently train the
            # plain conv stem while the user benchmarks "s2d".
            raise ValueError(
                f"unknown stem {self.stem!r}; have conv | space_to_depth")
        x = x.astype(self.dtype)
        if self.cifar_stem:
            x = conv(self.num_filters, (3, 3), name="conv_stem")(x)
            x = norm(name="bn_stem")(x)
            x = nn.relu(x)
        elif self.stem == "space_to_depth":
            if x.shape[1] % 2 or x.shape[2] % 2:
                raise ValueError(
                    f"space_to_depth stem needs even image dims, got "
                    f"{x.shape[1]}x{x.shape[2]}")
            x = SpaceToDepthStem(self.num_filters, dtype=self.dtype,
                                 param_dtype=self.param_dtype,
                                 name="conv_stem")(x)
            x = norm(name="bn_stem")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                     name="conv_stem")(x)
            x = norm(name="bn_stem")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))

        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(
                    filters=self.num_filters * 2**i,
                    conv=conv,
                    norm=norm,
                    strides=strides,
                    name=f"stage{i + 1}_block{j + 1}",
                )(x)

        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(
            self.num_classes,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=nn.initializers.normal(0.01),
            name="fc",
        )(x)
        return x.astype(jnp.float32)


def resnet18(cfg, dtype, param_dtype, cp=None) -> ResNet:
    del cp  # no sequence dim
    return ResNet(
        stage_sizes=(2, 2, 2, 2),
        block_cls=ResNetBlock,
        num_classes=cfg.num_classes,
        cifar_stem=cfg.image_size <= 64,
        stem=getattr(cfg, "stem", "conv"),
        dtype=dtype,
        param_dtype=param_dtype,
    )


def resnet50(cfg, dtype, param_dtype, cp=None) -> ResNet:
    del cp  # no sequence dim
    return ResNet(
        stage_sizes=(3, 4, 6, 3),
        block_cls=BottleneckBlock,
        num_classes=cfg.num_classes,
        cifar_stem=False,
        stem=getattr(cfg, "stem", "conv"),
        dtype=dtype,
        param_dtype=param_dtype,
    )
