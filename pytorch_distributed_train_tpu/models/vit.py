"""ViT-B/16 in Flax Linen (SURVEY H3; BASELINE.json:9).

Design notes (TPU-first, not a timm translation):
- Patch embedding is a strided conv in NHWC — one big MXU matmul per image.
- Attention goes through ops.attention.dot_product_attention (BSHD layout,
  fp32 softmax) so the Pallas flash kernel can slot in transparently.
- Learned position embeddings, prepended CLS token, pre-LN blocks, GELU MLP —
  the ViT-B/16 recipe the reference's config targets (bf16 + grad
  accumulation, BASELINE.json:9).
- LayerNorm in fp32 under a bf16 policy (same rationale as BN in resnet.py).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from pytorch_distributed_train_tpu.ops.attention import dot_product_attention


class MlpBlock(nn.Module):
    mlp_dim: int
    dropout_rate: float
    dtype: jnp.dtype
    param_dtype: jnp.dtype
    fused_epilogues: bool = False

    @nn.compact
    def __call__(self, x, deterministic: bool):
        d = x.shape[-1]
        if self.fused_epilogues:
            # Audit-driven bias+GELU epilogue (ops/fused_update.py):
            # param-compatible with the Dense+gelu pair below, same
            # exact-erf math, single tagged elementwise chain — the
            # "no_fused_epilogue" remat policy recomputes it backward.
            from pytorch_distributed_train_tpu.ops.fused_update import (
                FusedDenseGelu,
            )

            x = FusedDenseGelu(self.mlp_dim, dtype=self.dtype,
                               param_dtype=self.param_dtype,
                               name="mlp_in")(x)
        else:
            x = nn.Dense(self.mlp_dim, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="mlp_in")(x)
            x = nn.gelu(x, approximate=False)  # exact erf (torchvision/HF ViT)
        x = nn.Dropout(self.dropout_rate)(x, deterministic=deterministic)
        x = nn.Dense(d, dtype=self.dtype, param_dtype=self.param_dtype,
                     name="mlp_out")(x)
        x = nn.Dropout(self.dropout_rate)(x, deterministic=deterministic)
        return x


class MultiHeadAttention(nn.Module):
    num_heads: int
    dropout_rate: float
    dtype: jnp.dtype
    param_dtype: jnp.dtype
    attn_impl: str = "auto"  # threaded from ModelConfig.attention_impl

    @nn.compact
    def __call__(self, x, deterministic: bool):
        B, S, C = x.shape
        head_dim = C // self.num_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (self.num_heads, head_dim),
            axis=-1,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name=name,
        )
        q, k, v = dense("query")(x), dense("key")(x), dense("value")(x)
        y = dot_product_attention(q, k, v, impl=self.attn_impl)
        y = nn.DenseGeneral(
            C, axis=(-2, -1), dtype=self.dtype, param_dtype=self.param_dtype,
            name="attn_out",
        )(y)
        y = nn.Dropout(self.dropout_rate)(y, deterministic=deterministic)
        return y


class EncoderBlock(nn.Module):
    # `deterministic` is a module attribute, not a call arg, so nn.remat needs
    # no static_argnums bookkeeping (attributes are never traced).
    num_heads: int
    mlp_dim: int
    dropout_rate: float
    deterministic: bool
    dtype: jnp.dtype
    param_dtype: jnp.dtype
    attn_impl: str = "auto"
    fused_epilogues: bool = False

    @nn.compact
    def __call__(self, x):
        norm = lambda name: nn.LayerNorm(  # noqa: E731
            epsilon=1e-6, dtype=jnp.float32, param_dtype=jnp.float32, name=name
        )
        x = x + MultiHeadAttention(
            self.num_heads, self.dropout_rate, self.dtype, self.param_dtype,
            attn_impl=self.attn_impl, name="attn",
        )(norm("ln1")(x).astype(self.dtype), self.deterministic)
        x = x + MlpBlock(
            self.mlp_dim, self.dropout_rate, self.dtype, self.param_dtype,
            fused_epilogues=self.fused_epilogues, name="mlp",
        )(norm("ln2")(x).astype(self.dtype), self.deterministic)
        return x


class ViT(nn.Module):
    """Input: NHWC images. Output: (batch, num_classes) fp32 logits."""

    num_classes: int
    patch_size: int = 16
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    dropout_rate: float = 0.0
    remat: bool = False
    remat_policy: str = "full"  # full | dots | dots_no_batch |
    #                             no_fused_epilogue (models/remat.py)
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    attn_impl: str = "auto"
    fused_epilogues: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        deterministic = not train
        p = self.patch_size
        x = x.astype(self.dtype)
        x = nn.Conv(
            self.hidden_size, (p, p), strides=(p, p), padding="VALID",
            dtype=self.dtype, param_dtype=self.param_dtype, name="patch_embed",
        )(x)
        B, H, W, C = x.shape
        x = x.reshape(B, H * W, C)

        cls = self.param(
            "cls_token", nn.initializers.zeros, (1, 1, C), self.param_dtype
        )
        x = jnp.concatenate([jnp.broadcast_to(cls, (B, 1, C)).astype(self.dtype), x], axis=1)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (1, H * W + 1, C),
            self.param_dtype,
        )
        x = x + pos.astype(self.dtype)
        x = nn.Dropout(self.dropout_rate)(x, deterministic=deterministic)

        from pytorch_distributed_train_tpu.models.remat import remat_block

        block_cls = remat_block(EncoderBlock, self.remat, self.remat_policy)
        for i in range(self.num_layers):
            x = block_cls(
                self.num_heads, self.mlp_dim, self.dropout_rate, deterministic,
                self.dtype, self.param_dtype, attn_impl=self.attn_impl,
                fused_epilogues=self.fused_epilogues,
                name=f"block{i}",
            )(x)

        x = nn.LayerNorm(epsilon=1e-6, dtype=jnp.float32, param_dtype=jnp.float32,
                         name="ln_final")(x)
        x = x[:, 0]  # CLS token
        x = nn.Dense(
            self.num_classes, dtype=jnp.float32, param_dtype=self.param_dtype,
            kernel_init=nn.initializers.zeros, name="head",
        )(x)
        return x.astype(jnp.float32)


def vit_b16(cfg, dtype, param_dtype, cp=None) -> ViT:
    del cp  # patch-seq CP not useful at ViT scale (197 tokens)
    return ViT(
        attn_impl=getattr(cfg, "attention_impl", "auto"),
        fused_epilogues=getattr(cfg, "fused_epilogues", False),
        num_classes=cfg.num_classes,
        patch_size=cfg.patch_size,
        hidden_size=cfg.hidden_size,
        num_layers=cfg.num_layers,
        num_heads=cfg.num_heads,
        mlp_dim=cfg.mlp_dim,
        dropout_rate=cfg.dropout_rate,
        remat=cfg.remat,
        remat_policy=getattr(cfg, "remat_policy", "full"),
        dtype=dtype,
        param_dtype=param_dtype,
    )
