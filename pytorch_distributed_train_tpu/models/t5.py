"""T5 encoder-decoder (model-zoo extension beyond the BASELINE matrix).

The encoder-decoder archetype the zoo's decoder-only (llama/gpt2) and
encoder-only (bert/vit) families don't cover: a bidirectional encoder,
a causal decoder with CROSS-attention over the encoder output, bucketed
RELATIVE position biases instead of absolute/rotary embeddings, and a
shared input embedding table. Numerics follow HF transformers'
`T5ForConditionalGeneration` (v1.0, relu feed-forward) exactly — pinned
by the logits-parity tests against the torch implementation
(tests/test_hf_parity.py) in both head variants: untied (this repo's
training default) and tied+d_model**-0.5-rescaled
(`ModelConfig.tie_word_embeddings`, the published-checkpoint layout).

T5-specific conventions replicated (they bite anyone porting T5):
- attention scores are NOT scaled by 1/sqrt(head_dim) — the original
  checkpoints fold the scale into the weight init;
- the relative-attention-bias table lives in block 0 ONLY (one table for
  the encoder stack, one for the decoder stack) and the computed
  (H, Sq, Sk) bias is shared by every later block;
- T5's LayerNorm is scale-only RMS (no mean subtraction, no bias), with
  the mean-square computed in fp32;
- cross-attention has no position bias.

TPU notes: attention runs as explicit einsums with the additive bias
folded in before a fp32 softmax — XLA fuses bias+mask+softmax into the
score matmul's epilogue. The Pallas flash kernel doesn't carry additive
bias (it would need a bias-tile stream); at T5's typical 512-token
encoder lengths the dense path is MXU-bound anyway.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn


# T5's LayerNorm IS llama's RMSNorm (scale-only, fp32 mean-square, no
# mean subtraction) — one implementation in the zoo, eps=1e-6 here.
from pytorch_distributed_train_tpu.models.llama import (
    RMSNorm,
    resolve_kv_dtype,
)  # noqa: E402


def relative_position_bucket(relative_position, bidirectional: bool,
                             num_buckets: int, max_distance: int):
    """HF `_relative_position_bucket`: exact log-spaced bucketing.

    relative_position = key_pos - query_pos, int32 array. Encoder
    (bidirectional) splits buckets by sign; decoder buckets only the
    causal past. Near positions get exact buckets, far positions log-
    spaced up to max_distance."""
    rp = relative_position
    buckets = jnp.zeros_like(rp)
    if bidirectional:
        num_buckets //= 2
        buckets = buckets + (rp > 0).astype(jnp.int32) * num_buckets
        rp = jnp.abs(rp)
    else:
        rp = -jnp.minimum(rp, 0)
    max_exact = num_buckets // 2
    is_small = rp < max_exact
    large = max_exact + (
        jnp.log(rp.astype(jnp.float32) / max_exact + 1e-9)
        / np.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    large = jnp.minimum(large, num_buckets - 1)
    return buckets + jnp.where(is_small, rp, large)


class T5Attention(nn.Module):
    """Self- or cross-attention, T5 numerics (no 1/sqrt(d) scale).

    When ``rel_bias`` this module OWNS the stack's relative-bias table
    and returns the computed bias for reuse by later blocks; callers pass
    ``position_bias`` back in for the biasless blocks."""

    num_heads: int
    rel_bias: bool
    bidirectional: bool
    rel_pos_buckets: int
    rel_pos_max_distance: int
    dropout_rate: float
    deterministic: bool
    dtype: jnp.dtype
    param_dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, kv=None, mask=None, position_bias=None):
        B, Sq, C = x.shape
        kv = x if kv is None else kv
        Sk = kv.shape[1]
        head_dim = C // self.num_heads
        # T5's scaled init is what makes UNSCALED attention scores sane at
        # step 0: q ~ N(0, (d_model*d_kv)^-0.5), k/v/o ~ N(0, d_model^-0.5)
        # (HF T5PreTrainedModel._init_weights with factor=1).
        q_std = (C * head_dim) ** -0.5
        kv_std = C ** -0.5
        proj = lambda heads, std, name: nn.DenseGeneral(  # noqa: E731
            (heads, head_dim), axis=-1, use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=nn.initializers.normal(std), name=name,
        )
        q = proj(self.num_heads, q_std, "q_proj")(x)    # (B, Sq, H, D)
        k = proj(self.num_heads, kv_std, "k_proj")(kv)  # (B, Sk, H, D)
        v = proj(self.num_heads, kv_std, "v_proj")(kv)
        # T5: unscaled scores (the 1/sqrt(d) lives in the checkpoint init)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32)
        if self.rel_bias:
            # HF inits the bias table at d_model**-0.5 like k/v/o
            table = nn.Embed(
                self.rel_pos_buckets, self.num_heads,
                embedding_init=nn.initializers.normal(C ** -0.5),
                param_dtype=self.param_dtype, name="rel_bias")
            rel = (jnp.arange(Sk)[None, :]
                   - jnp.arange(Sq)[:, None]).astype(jnp.int32)
            buckets = relative_position_bucket(
                rel, self.bidirectional, self.rel_pos_buckets,
                self.rel_pos_max_distance)
            position_bias = jnp.transpose(
                table(buckets), (2, 0, 1))[None]      # (1, H, Sq, Sk)
            position_bias = position_bias.astype(jnp.float32)
        if position_bias is not None:
            scores = scores + position_bias
        if mask is not None:
            scores = jnp.where(mask, scores, jnp.float32(-1e9))
        probs = jax.nn.softmax(scores, axis=-1).astype(self.dtype)
        # HF T5 drops out the attention PROBABILITIES too, not just the
        # sublayer outputs.
        probs = nn.Dropout(self.dropout_rate)(
            probs, deterministic=self.deterministic)
        y = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        out = nn.DenseGeneral(
            C, axis=(-2, -1), use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=nn.initializers.normal(kv_std), name="o_proj",
        )(y)
        return out, position_bias


class T5MLP(nn.Module):
    """v1.0 DenseReluDense: wi -> relu -> wo, no biases."""

    mlp_dim: int
    dropout_rate: float
    deterministic: bool
    dtype: jnp.dtype
    param_dtype: jnp.dtype

    @nn.compact
    def __call__(self, x):
        # HF scaled init: wi ~ N(0, d_model^-0.5), wo ~ N(0, d_ff^-0.5)
        dense = lambda features, std, name: nn.Dense(  # noqa: E731
            features, use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=nn.initializers.normal(std), name=name)
        h = nn.relu(dense(self.mlp_dim, x.shape[-1] ** -0.5, "wi")(x))
        h = nn.Dropout(self.dropout_rate)(h, deterministic=self.deterministic)
        return dense(x.shape[-1], self.mlp_dim ** -0.5, "wo")(h)


class T5DecodeAttention(nn.Module):
    """Single-token decoder SELF-attention with a KV cache (generation
    path, generate.generate_seq2seq). Mirrors llama's decode discipline:
    static (B, L, H, D) buffers + a cache_index (scalar, or (B,) under
    ``decode_rows`` — serving.py's per-row offsets), absolute-position
    masking of the unwritten tail. The block-0 relative-bias table is
    looked up per step for the query's absolute position; later blocks
    receive the computed bias ((1, H, 1, L), or (B, H, 1, L) per-row)."""

    num_heads: int
    rel_bias: bool
    rel_pos_buckets: int
    rel_pos_max_distance: int
    max_len: int
    dtype: jnp.dtype
    param_dtype: jnp.dtype
    # Per-row cache offsets for continuous batching (serving.py) — same
    # contract as llama/gpt2 decode_rows: cache_index is (B,), and the
    # relative-position bias / mask are computed per row.
    decode_rows: bool = False
    kv_cache_dtype: str = ""  # cache STORAGE dtype (llama.py contract)

    @nn.compact
    def __call__(self, x, position_bias=None):
        B, S, C = x.shape
        assert S == 1, "decode steps are single-token"
        head_dim = C // self.num_heads
        q_std = (C * head_dim) ** -0.5
        kv_std = C ** -0.5
        proj = lambda std, name: nn.DenseGeneral(  # noqa: E731
            (self.num_heads, head_dim), axis=-1, use_bias=False,
            dtype=self.dtype, param_dtype=self.param_dtype,
            kernel_init=nn.initializers.normal(std), name=name,
        )
        q = proj(q_std, "q_proj")(x)
        k = proj(kv_std, "k_proj")(x)
        v = proj(kv_std, "v_proj")(x)
        L = self.max_len
        cdt = resolve_kv_dtype(self.kv_cache_dtype, k.dtype)
        c_k = self.variable("cache", "cached_key", jnp.zeros,
                            (B, L, self.num_heads, head_dim), cdt)
        c_v = self.variable("cache", "cached_value", jnp.zeros,
                            (B, L, self.num_heads, head_dim), cdt)
        idx_shape = (B,) if self.decode_rows else ()
        c_i = self.variable("cache", "cache_index",
                            lambda: jnp.zeros(idx_shape, jnp.int32))
        idx = c_i.value
        if self.decode_rows:
            upd = lambda c, new, i: jax.lax.dynamic_update_slice_in_dim(  # noqa: E731
                c, new, i, 0)
            c_k.value = jax.vmap(upd)(c_k.value, k.astype(cdt), idx)
            c_v.value = jax.vmap(upd)(c_v.value, v.astype(cdt), idx)
        else:
            c_k.value = jax.lax.dynamic_update_slice_in_dim(
                c_k.value, k.astype(cdt), idx, 1)
            c_v.value = jax.lax.dynamic_update_slice_in_dim(
                c_v.value, v.astype(cdt), idx, 1)
        c_i.value = idx + 1
        k_pos = jnp.arange(L)
        if self.rel_bias:
            # HF inits the bias table at d_model**-0.5 like k/v/o
            table = nn.Embed(
                self.rel_pos_buckets, self.num_heads,
                embedding_init=nn.initializers.normal(C ** -0.5),
                param_dtype=self.param_dtype, name="rel_bias")
            if self.decode_rows:
                # (B, L) relative distances — one bias row per slot offset
                rel = (k_pos[None, :] - idx[:, None]).astype(jnp.int32)
                buckets = relative_position_bucket(
                    rel, False, self.rel_pos_buckets,
                    self.rel_pos_max_distance)
                position_bias = jnp.transpose(
                    table(buckets), (0, 2, 1))[:, :, None, :]  # (B,H,1,L)
            else:
                buckets = relative_position_bucket(
                    (k_pos - idx).astype(jnp.int32), False,
                    self.rel_pos_buckets, self.rel_pos_max_distance)
                position_bias = jnp.transpose(
                    table(buckets), (1, 0))[None, :, None, :]  # (1,H,1,L)
            position_bias = position_bias.astype(jnp.float32)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q,
                            c_k.value.astype(self.dtype),
                            preferred_element_type=jnp.float32)
        if position_bias is not None:
            scores = scores + position_bias
        live = (k_pos[None, None, None, :]
                <= (idx[:, None, None, None] if self.decode_rows else idx))
        scores = jnp.where(live, scores, jnp.float32(-1e9))
        probs = jax.nn.softmax(scores, axis=-1).astype(self.dtype)
        y = jnp.einsum("bhqk,bkhd->bqhd", probs,
                       c_v.value.astype(self.dtype))
        out = nn.DenseGeneral(
            C, axis=(-2, -1), use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=nn.initializers.normal(kv_std), name="o_proj",
        )(y)
        return out, position_bias


class T5Block(nn.Module):
    num_heads: int
    mlp_dim: int
    rel_bias: bool          # block 0 owns the stack's bias table
    is_decoder: bool
    rel_pos_buckets: int
    rel_pos_max_distance: int
    eps: float
    dropout_rate: float
    deterministic: bool
    dtype: jnp.dtype
    param_dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, enc=None, self_mask=None, cross_mask=None,
                 position_bias=None):
        drop = lambda h: nn.Dropout(self.dropout_rate)(  # noqa: E731
            h, deterministic=self.deterministic)
        attn = partial(
            T5Attention, self.num_heads,
            rel_pos_buckets=self.rel_pos_buckets,
            rel_pos_max_distance=self.rel_pos_max_distance,
            dropout_rate=self.dropout_rate,
            deterministic=self.deterministic,
            dtype=self.dtype, param_dtype=self.param_dtype)

        h = RMSNorm(self.eps, name="ln_self")(x)
        h, position_bias = attn(
            rel_bias=self.rel_bias, bidirectional=not self.is_decoder,
            name="self_attn",
        )(h, mask=self_mask, position_bias=position_bias)
        x = x + drop(h)
        if self.is_decoder:
            h = RMSNorm(self.eps, name="ln_cross")(x)
            h, _ = attn(rel_bias=False, bidirectional=True,
                        name="cross_attn")(h, kv=enc, mask=cross_mask)
            x = x + drop(h)
        h = RMSNorm(self.eps, name="ln_mlp")(x)
        h = T5MLP(self.mlp_dim, self.dropout_rate, self.deterministic,
                  self.dtype, self.param_dtype, name="mlp")(h)
        return x + drop(h), position_bias


class T5ForConditionalGeneration(nn.Module):
    """Inputs: (input_ids (B,Se), decoder_input_ids (B,Sd)); optional
    encoder ``attention_mask``. Output: (B, Sd, vocab) fp32 logits."""

    vocab_size: int
    hidden_size: int = 512
    num_layers: int = 6          # encoder depth
    decoder_layers: int = 0      # 0 -> = num_layers
    num_heads: int = 8
    mlp_dim: int = 2048
    rel_pos_buckets: int = 32
    rel_pos_max_distance: int = 128
    dropout_rate: float = 0.1
    layer_norm_eps: float = 1e-6
    # v1.0 published checkpoints tie the head to `shared` and rescale the
    # decoder output by d_model**-0.5 before it (HF applies the rescale
    # only when tied); untied is this repo's training default.
    tie_head: bool = False
    # Activation rematerialization per block (models/remat.py policies)
    remat: bool = False
    remat_policy: str = "full"
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, input_ids, decoder_input_ids, train: bool = True,
                 attention_mask=None, loss_mask=None):
        del loss_mask  # seq2seq loss reads weights from the batch
        det = not train
        n_dec = self.decoder_layers or self.num_layers
        shared = nn.Embed(
            self.vocab_size, self.hidden_size,
            embedding_init=nn.initializers.normal(1.0),
            param_dtype=self.param_dtype, name="shared")
        drop = lambda h: nn.Dropout(self.dropout_rate)(  # noqa: E731
            h, deterministic=det)
        from pytorch_distributed_train_tpu.models.remat import remat_block

        block_cls = remat_block(T5Block, self.remat, self.remat_policy)
        block = partial(
            block_cls, self.num_heads, self.mlp_dim,
            rel_pos_buckets=self.rel_pos_buckets,
            rel_pos_max_distance=self.rel_pos_max_distance,
            eps=self.layer_norm_eps, dropout_rate=self.dropout_rate,
            deterministic=det, dtype=self.dtype,
            param_dtype=self.param_dtype)

        # ---- encoder
        Se = input_ids.shape[1]
        enc_mask = None
        if attention_mask is not None:
            enc_mask = attention_mask[:, None, None, :].astype(bool)
        x = drop(shared(input_ids).astype(self.dtype))
        bias = None
        for i in range(self.num_layers):
            x, bias = block(rel_bias=i == 0, is_decoder=False,
                            name=f"enc_block{i}")(
                x, self_mask=enc_mask, position_bias=bias)
        enc = drop(RMSNorm(self.layer_norm_eps, name="enc_final_norm")(x))

        # ---- decoder
        Sd = decoder_input_ids.shape[1]
        causal = jnp.tril(jnp.ones((Sd, Sd), bool))[None, None]
        cross_mask = enc_mask  # (B,1,1,Se) broadcasts over decoder queries
        y = drop(shared(decoder_input_ids).astype(self.dtype))
        bias = None
        for i in range(n_dec):
            y, bias = block(rel_bias=i == 0, is_decoder=True,
                            name=f"dec_block{i}")(
                y, enc=enc, self_mask=causal, cross_mask=cross_mask,
                position_bias=bias)
        y = drop(RMSNorm(self.layer_norm_eps, name="dec_final_norm")(y))

        if self.tie_head:
            y = y * (self.hidden_size ** -0.5)
            emb = jnp.asarray(shared.embedding, self.dtype)
            logits = jax.lax.dot_general(
                y, emb, (((2,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
        else:
            logits = nn.Dense(
                self.vocab_size, use_bias=False, dtype=self.dtype,
                param_dtype=self.param_dtype,
                dot_general=partial(jax.lax.dot_general,
                                    preferred_element_type=jnp.float32),
                kernel_init=nn.initializers.normal(1.0),  # HF: factor*1.0
                name="lm_head",
            )(y)
        return logits.astype(jnp.float32)


def t5(cfg, dtype, param_dtype, cp=None, act=None) -> T5ForConditionalGeneration:
    """Registry ctor. Encoder-decoder context parallelism is not
    implemented — refuse loudly rather than silently train without the
    ring/Ulysses path the mesh asked for."""
    if cp is not None:
        raise ValueError(
            "t5 does not support context parallelism (mesh context>1): "
            "the encoder-decoder attention stack has no ring/Ulysses "
            "routing — use context=1 for t5 runs")
    del act
    return T5ForConditionalGeneration(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.hidden_size,
        num_layers=cfg.num_layers,
        decoder_layers=getattr(cfg, "decoder_layers", 0),
        num_heads=cfg.num_heads,
        mlp_dim=cfg.mlp_dim,
        rel_pos_buckets=getattr(cfg, "rel_pos_buckets", 32),
        rel_pos_max_distance=getattr(cfg, "rel_pos_max_distance", 128),
        dropout_rate=cfg.dropout_rate,
        tie_head=getattr(cfg, "tie_word_embeddings", False),
        remat=getattr(cfg, "remat", False),
        remat_policy=getattr(cfg, "remat_policy", "full"),
        dtype=dtype,
        param_dtype=param_dtype,
    )


class T5DecodeBlock(nn.Module):
    """Decoder block for single-token generation: cached self-attention
    (T5DecodeAttention), cross-attention over the fixed encoder output,
    MLP. Submodule names mirror T5Block's decoder layout exactly, so the
    TRAINING param tree drives decoding unchanged."""

    num_heads: int
    mlp_dim: int
    rel_bias: bool
    rel_pos_buckets: int
    rel_pos_max_distance: int
    eps: float
    max_len: int
    dtype: jnp.dtype
    param_dtype: jnp.dtype
    decode_rows: bool = False
    kv_cache_dtype: str = ""

    @nn.compact
    def __call__(self, x, enc, enc_mask=None, position_bias=None):
        h = RMSNorm(self.eps, name="ln_self")(x)
        h, position_bias = T5DecodeAttention(
            self.num_heads, rel_bias=self.rel_bias,
            rel_pos_buckets=self.rel_pos_buckets,
            rel_pos_max_distance=self.rel_pos_max_distance,
            max_len=self.max_len, dtype=self.dtype,
            param_dtype=self.param_dtype, decode_rows=self.decode_rows,
            kv_cache_dtype=self.kv_cache_dtype,
            name="self_attn",
        )(h, position_bias=position_bias)
        x = x + h
        h = RMSNorm(self.eps, name="ln_cross")(x)
        # Cross K/V are recomputed from `enc` each step (two (Se,C,inner)
        # matmuls per layer per token) rather than cached — simpler, and
        # at T5 shapes the self-attn weight streaming dominates anyway.
        h, _ = T5Attention(
            self.num_heads, rel_bias=False, bidirectional=True,
            rel_pos_buckets=self.rel_pos_buckets,
            rel_pos_max_distance=self.rel_pos_max_distance,
            dropout_rate=0.0, deterministic=True, dtype=self.dtype,
            param_dtype=self.param_dtype, name="cross_attn",
        )(h, kv=enc, mask=enc_mask)
        x = x + h
        h = RMSNorm(self.eps, name="ln_mlp")(x)
        h = T5MLP(self.mlp_dim, 0.0, True, self.dtype, self.param_dtype,
                  name="mlp")(h)
        return x + h, position_bias


class T5Encoder(nn.Module):
    """Encoder-only forward (generation prefill). Same param names as the
    full model's encoder half."""

    vocab_size: int
    hidden_size: int
    num_layers: int
    num_heads: int
    mlp_dim: int
    rel_pos_buckets: int
    rel_pos_max_distance: int
    layer_norm_eps: float
    dtype: jnp.dtype
    param_dtype: jnp.dtype

    @nn.compact
    def __call__(self, input_ids, attention_mask=None):
        shared = nn.Embed(
            self.vocab_size, self.hidden_size,
            embedding_init=nn.initializers.normal(1.0),
            param_dtype=self.param_dtype, name="shared")
        enc_mask = None
        if attention_mask is not None:
            enc_mask = attention_mask[:, None, None, :].astype(bool)
        x = shared(input_ids).astype(self.dtype)
        bias = None
        for i in range(self.num_layers):
            x, bias = T5Block(
                self.num_heads, self.mlp_dim, rel_bias=i == 0,
                is_decoder=False, rel_pos_buckets=self.rel_pos_buckets,
                rel_pos_max_distance=self.rel_pos_max_distance,
                eps=self.layer_norm_eps, dropout_rate=0.0,
                deterministic=True, dtype=self.dtype,
                param_dtype=self.param_dtype, name=f"enc_block{i}",
            )(x, self_mask=enc_mask, position_bias=bias)
        return RMSNorm(self.layer_norm_eps, name="enc_final_norm")(x)


class T5DecodeStep(nn.Module):
    """One decoder token against a fixed encoder output, KV cache in the
    flax 'cache' collection. Param names mirror the training model, so
    ``model.apply({'params': train_params, 'cache': cache}, ...)`` works
    directly."""

    vocab_size: int
    hidden_size: int
    decoder_layers: int
    num_heads: int
    mlp_dim: int
    rel_pos_buckets: int
    rel_pos_max_distance: int
    layer_norm_eps: float
    max_decode_len: int
    tie_head: bool
    dtype: jnp.dtype
    param_dtype: jnp.dtype
    decode_rows: bool = False
    kv_cache_dtype: str = ""

    @nn.compact
    def __call__(self, dec_ids, enc, enc_mask=None):
        shared = nn.Embed(
            self.vocab_size, self.hidden_size,
            embedding_init=nn.initializers.normal(1.0),
            param_dtype=self.param_dtype, name="shared")
        mask4 = None
        if enc_mask is not None:
            mask4 = enc_mask[:, None, None, :].astype(bool)
        y = shared(dec_ids).astype(self.dtype)
        bias = None
        for i in range(self.decoder_layers):
            y, bias = T5DecodeBlock(
                self.num_heads, self.mlp_dim, rel_bias=i == 0,
                rel_pos_buckets=self.rel_pos_buckets,
                rel_pos_max_distance=self.rel_pos_max_distance,
                eps=self.layer_norm_eps, max_len=self.max_decode_len,
                dtype=self.dtype, param_dtype=self.param_dtype,
                decode_rows=self.decode_rows,
                kv_cache_dtype=self.kv_cache_dtype,
                name=f"dec_block{i}",
            )(y, enc, enc_mask=mask4, position_bias=bias)
        y = RMSNorm(self.layer_norm_eps, name="dec_final_norm")(y)
        if self.tie_head:
            y = y * (self.hidden_size ** -0.5)
            emb = jnp.asarray(shared.embedding, self.dtype)
            logits = jax.lax.dot_general(
                y, emb, (((2,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
        else:
            logits = nn.Dense(
                self.vocab_size, use_bias=False, dtype=self.dtype,
                param_dtype=self.param_dtype,
                dot_general=partial(jax.lax.dot_general,
                                    preferred_element_type=jnp.float32),
                kernel_init=nn.initializers.normal(1.0),  # HF: factor*1.0
                name="lm_head",
            )(y)
        return logits.astype(jnp.float32)


def t5_encoder(cfg, dtype, param_dtype) -> T5Encoder:
    return T5Encoder(
        vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
        num_layers=cfg.num_layers, num_heads=cfg.num_heads,
        mlp_dim=cfg.mlp_dim,
        rel_pos_buckets=getattr(cfg, "rel_pos_buckets", 32),
        rel_pos_max_distance=getattr(cfg, "rel_pos_max_distance", 128),
        layer_norm_eps=1e-6, dtype=dtype, param_dtype=param_dtype)


def t5_decode_step(cfg, dtype, param_dtype, max_decode_len: int,
                   decode_rows: bool = False) -> T5DecodeStep:
    resolve_kv_dtype(getattr(cfg, "kv_cache_dtype", ""), dtype)  # validate
    return T5DecodeStep(
        decode_rows=decode_rows,
        kv_cache_dtype=getattr(cfg, "kv_cache_dtype", ""),
        vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
        decoder_layers=getattr(cfg, "decoder_layers", 0) or cfg.num_layers,
        num_heads=cfg.num_heads, mlp_dim=cfg.mlp_dim,
        rel_pos_buckets=getattr(cfg, "rel_pos_buckets", 32),
        rel_pos_max_distance=getattr(cfg, "rel_pos_max_distance", 128),
        layer_norm_eps=1e-6, max_decode_len=max_decode_len,
        tie_head=getattr(cfg, "tie_word_embeddings", False),
        dtype=dtype, param_dtype=param_dtype)
