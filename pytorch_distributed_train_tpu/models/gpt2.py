"""GPT-2 decoder (model-zoo extension beyond the BASELINE matrix).

The classic pre-LN decoder: learned token + position embeddings, blocks of
ln_1 → attention → residual, ln_2 → MLP(gelu_tanh) → residual, final LN,
and a TIED lm head (logits = h @ wte^T) — the architecture of the HF/torch
``gpt2`` checkpoints, so weights round-trip through interop
(`to_hf_state_dict(..., "gpt2")`) and logits parity is testable against
``transformers.GPT2LMHeadModel`` (tests/test_hf_parity.py).

TPU notes mirror the other LMs: BSHD attention through ops.attention
(fp32 softmax, backend-dispatched), fp32-accumulated bf16 head matmul,
activations castable to the compute dtype throughout. GELU is the tanh
approximation — GPT-2's ``gelu_new``, unlike BERT/ViT's exact erf.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from pytorch_distributed_train_tpu.models.llama import resolve_kv_dtype
from pytorch_distributed_train_tpu.ops.attention import (
    ContextParallelConfig,
    dot_product_attention,
)


class GPT2Attention(nn.Module):
    num_heads: int
    max_seq_len: int
    dtype: jnp.dtype
    param_dtype: jnp.dtype
    cp: ContextParallelConfig | None = None
    attn_impl: str = "auto"
    window: int = 0  # sliding-window attention (0 = full causal)
    quant: str = ""  # "" | "int8" (quant.int8_dot_general QAT matmuls)
    kv_cache_dtype: str = ""  # cache STORAGE dtype (llama.py contract)
    decode: bool = False  # KV cache (same contract as llama.py decode)
    # S>1 appends at the running offset instead of prefilling from 0
    # (speculative.py's verify pass — same contract as llama.py)
    decode_multi: bool = False
    # Per-row cache offsets for continuous batching (serving.py) — same
    # contract as llama.py decode_rows: cache_index is (B,)
    decode_rows: bool = False

    @nn.compact
    def __call__(self, x, segments=None):
        from pytorch_distributed_train_tpu.quant import quant_dot_general

        B, S, C = x.shape
        head_dim = C // self.num_heads
        dg = quant_dot_general(self.quant)
        proj = lambda name: nn.DenseGeneral(  # noqa: E731
            (self.num_heads, head_dim), axis=-1, dtype=self.dtype,
            param_dtype=self.param_dtype, dot_general=dg,
            kernel_init=nn.initializers.normal(0.02), name=name,
        )
        q, k, v = proj("q_proj")(x), proj("k_proj")(x), proj("v_proj")(x)
        if self.decode:
            L = self.max_seq_len
            cdt = resolve_kv_dtype(self.kv_cache_dtype, k.dtype)
            c_k = self.variable("cache", "cached_key", jnp.zeros,
                                (B, L, self.num_heads, head_dim), cdt)
            c_v = self.variable("cache", "cached_value", jnp.zeros,
                                (B, L, self.num_heads, head_dim), cdt)
            # decode_rows + decode_multi = MULTI-TOKEN rows continuation
            # (serving.py session resume ingests a whole user turn at each
            # row's offset); plain decode_rows steps are its S=1 case.
            idx_shape = (B,) if self.decode_rows else ()
            c_i = self.variable("cache", "cache_index",
                                lambda: jnp.zeros(idx_shape, jnp.int32))
            if S > 1 and not self.decode_multi:
                # prefill from position 0 (generate.py contract)
                c_k.value = jax.lax.dynamic_update_slice_in_dim(
                    c_k.value, k.astype(cdt), 0, 1)
                c_v.value = jax.lax.dynamic_update_slice_in_dim(
                    c_v.value, v.astype(cdt), 0, 1)
                c_i.value = jnp.full(idx_shape, S, jnp.int32)
                y = dot_product_attention(q, k, v, causal=True,
                                          impl=self.attn_impl,
                                          window=self.window)
            elif self.decode_rows:
                # per-row continuation (cf. llama.py): row b's S tokens
                # append at ITS offset idx[b]; vmap'd updates, per-row mask
                idx = c_i.value  # (B,)
                upd = lambda c, new, i: jax.lax.dynamic_update_slice_in_dim(  # noqa: E731
                    c, new, i, 0)
                c_k.value = jax.vmap(upd)(c_k.value, k.astype(cdt), idx)
                c_v.value = jax.vmap(upd)(c_v.value, v.astype(cdt), idx)
                c_i.value = idx + S
                q_pos = idx[:, None] + jnp.arange(S)  # (B, S)
                k_pos = jnp.arange(L)
                mask = k_pos[None, None, :] <= q_pos[:, :, None]
                if self.window:
                    mask &= (q_pos[:, :, None] - k_pos[None, None, :]
                             ) < self.window
                y = dot_product_attention(q, c_k.value.astype(self.dtype),
                                          c_v.value.astype(self.dtype),
                                          mask=mask[:, None], impl="xla")
            else:
                idx = c_i.value
                c_k.value = jax.lax.dynamic_update_slice_in_dim(
                    c_k.value, k.astype(cdt), idx, 1)
                c_v.value = jax.lax.dynamic_update_slice_in_dim(
                    c_v.value, v.astype(cdt), idx, 1)
                c_i.value = idx + S
                q_pos = idx + jnp.arange(S)
                k_pos = jnp.arange(L)
                mask = k_pos[None, :] <= q_pos[:, None]
                if self.window:
                    mask &= (q_pos[:, None] - k_pos[None, :]) < self.window
                mask = mask[None, None]
                y = dot_product_attention(q, c_k.value.astype(self.dtype),
                                          c_v.value.astype(self.dtype),
                                          mask=mask, impl="xla")
        else:
            y = dot_product_attention(q, k, v, causal=True, cp=self.cp,
                                      impl=self.attn_impl,
                                      window=self.window, segments=segments)
        return nn.DenseGeneral(
            C, axis=(-2, -1), dtype=self.dtype, param_dtype=self.param_dtype,
            dot_general=dg,
            kernel_init=nn.initializers.normal(0.02), name="c_proj",
        )(y)


class GPT2Block(nn.Module):
    num_heads: int
    mlp_dim: int
    max_seq_len: int
    dropout_rate: float
    deterministic: bool
    dtype: jnp.dtype
    param_dtype: jnp.dtype
    cp: ContextParallelConfig | None = None
    attn_impl: str = "auto"
    window: int = 0
    quant: str = ""
    kv_cache_dtype: str = ""
    decode: bool = False
    decode_multi: bool = False
    decode_rows: bool = False

    @nn.compact
    def __call__(self, x, segments=None):
        from pytorch_distributed_train_tpu.quant import quant_dot_general

        ln = lambda name: nn.LayerNorm(  # noqa: E731
            epsilon=1e-5, dtype=jnp.float32, param_dtype=jnp.float32,
            name=name,
        )
        h = ln("ln_1")(x).astype(self.dtype)
        x = x + nn.Dropout(self.dropout_rate)(
            GPT2Attention(self.num_heads, self.max_seq_len, self.dtype,
                          self.param_dtype, cp=self.cp,
                          attn_impl=self.attn_impl, window=self.window,
                          quant=self.quant,
                          kv_cache_dtype=self.kv_cache_dtype,
                          decode=self.decode,
                          decode_multi=self.decode_multi,
                          decode_rows=self.decode_rows,
                          name="attn")(h, segments=segments),
            deterministic=self.deterministic)
        h = ln("ln_2")(x).astype(self.dtype)
        dg = quant_dot_general(self.quant)
        h = nn.Dense(self.mlp_dim, dtype=self.dtype,
                     param_dtype=self.param_dtype, dot_general=dg,
                     kernel_init=nn.initializers.normal(0.02),
                     name="c_fc")(h)
        h = nn.gelu(h)  # tanh approximation == GPT-2's gelu_new
        h = nn.Dense(x.shape[-1], dtype=self.dtype,
                     param_dtype=self.param_dtype, dot_general=dg,
                     kernel_init=nn.initializers.normal(0.02),
                     name="c_proj")(h)
        return x + nn.Dropout(self.dropout_rate)(
            h, deterministic=self.deterministic)


class GPT2LMHead(nn.Module):
    """Input: (B, S) int ids. Output: (B, S, vocab) fp32 logits."""

    vocab_size: int
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    max_seq_len: int = 1024
    dropout_rate: float = 0.0
    remat: bool = False
    remat_policy: str = "full"  # full | dots | dots_no_batch (models/remat.py)
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    cp: ContextParallelConfig | None = None
    attn_impl: str = "auto"
    attention_window: int = 0  # sliding window (0 = full causal)
    quant_training: str = ""  # "" | "int8" AQT matmuls (tied head stays fp)
    kv_cache_dtype: str = ""  # cache STORAGE dtype (llama.py contract)
    decode: bool = False  # KV-cache autoregressive mode (generate.py)
    # Multi-token continuation in decode mode (speculative.py verify pass)
    decode_multi: bool = False
    # Per-row cache/position offsets for continuous batching (serving.py)
    decode_rows: bool = False
    # Fused chunked head+CE over the tied embedding (losses.chunked_causal_ce)
    fused_loss: bool = False
    # Packed-block document isolation (see llama.py segment_eos_id)
    segment_eos_id: int = -1
    act: "object | None" = None

    @nn.compact
    def __call__(self, input_ids, train: bool = True, loss_mask=None):
        deterministic = not train
        B, S = input_ids.shape
        segments = seg_positions = None
        if self.segment_eos_id >= 0:
            if self.decode:
                raise ValueError(
                    "segment_eos_id is a packed-TRAINING feature; decode "
                    "serves one unpacked sequence per row")
            if self.cp is not None and self.cp.active:
                raise ValueError(
                    "segment_eos_id with context parallelism is not "
                    "supported; use context=1 for packed-isolation runs")
            from pytorch_distributed_train_tpu.models.llama import (
                packed_segments,
            )

            segments, seg_positions = packed_segments(input_ids,
                                                      self.segment_eos_id)
        wte = nn.Embed(self.vocab_size, self.hidden_size,
                       embedding_init=nn.initializers.normal(0.02),
                       param_dtype=self.param_dtype, name="wte")
        wpe = self.param("wpe", nn.initializers.normal(0.01),
                         (self.max_seq_len, self.hidden_size),
                         self.param_dtype)
        pos_shape = (B,) if self.decode_rows else ()
        if self.decode and (S == 1 or self.decode_multi):
            # step(s) at the running offset: single-token decode, or a
            # multi-token continuation (speculative.py verify — positions
            # are the absolute idx..idx+S-1, same as the attention cache).
            # decode_rows: each row slices wpe at ITS own offset.
            p_i = self.variable("cache", "pos_index",
                                lambda: jnp.zeros(pos_shape, jnp.int32))
            if self.decode_rows:
                pos = jax.vmap(
                    lambda i: jax.lax.dynamic_slice_in_dim(wpe, i, S, 0)
                )(p_i.value)  # (B, S, C)
            else:
                pos = jax.lax.dynamic_slice_in_dim(wpe, p_i.value, S, 0)[None]
            p_i.value = p_i.value + S
        else:
            # packed segments: each document's positions restart at 0
            pos = (wpe[seg_positions] if seg_positions is not None
                   else wpe[:S][None])
            if self.decode:
                p_i = self.variable("cache", "pos_index",
                                    lambda: jnp.zeros(pos_shape, jnp.int32))
                p_i.value = jnp.full(pos_shape, S, jnp.int32)
        x = wte(input_ids) + pos
        x = nn.Dropout(self.dropout_rate)(x, deterministic=deterministic)
        x = x.astype(self.dtype)
        if self.act is not None:
            x = self.act.constrain(x)

        from pytorch_distributed_train_tpu.models.remat import remat_block

        block_cls = remat_block(GPT2Block, self.remat, self.remat_policy)
        for i in range(self.num_layers):
            x = block_cls(
                self.num_heads, self.mlp_dim, self.max_seq_len,
                self.dropout_rate, deterministic, self.dtype,
                self.param_dtype, cp=self.cp, attn_impl=self.attn_impl,
                window=self.attention_window, quant=self.quant_training,
                kv_cache_dtype=self.kv_cache_dtype,
                decode=self.decode, decode_multi=self.decode_multi,
                decode_rows=self.decode_rows,
                name=f"h{i}",
            )(x, segments=segments)
            if self.act is not None:
                x = self.act.constrain(x)

        x = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32,
                         param_dtype=jnp.float32, name="ln_f")(x)
        # Tied head, bf16 operands with fp32 accumulation (cf. bert.py).
        emb = jnp.asarray(wte.embedding, self.dtype)  # (V, C)
        if self.fused_loss and not self.decode:
            from pytorch_distributed_train_tpu.losses import chunked_causal_ce

            return chunked_causal_ce(x.astype(self.dtype), emb, input_ids,
                                     loss_mask=loss_mask,
                                     transpose_kernel=True)
        logits = jax.lax.dot_general(
            x.astype(self.dtype), emb,
            (((x.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return logits.astype(jnp.float32)


def gpt2(cfg, dtype, param_dtype, cp=None, act=None) -> GPT2LMHead:
    resolve_kv_dtype(getattr(cfg, "kv_cache_dtype", ""), dtype)  # validate NOW
    return GPT2LMHead(
        cp=cp,
        act=act,
        attn_impl=getattr(cfg, "attention_impl", "auto"),
        attention_window=getattr(cfg, "attention_window", 0),
        kv_cache_dtype=getattr(cfg, "kv_cache_dtype", ""),
        quant_training=getattr(cfg, "quant_training", ""),
        segment_eos_id=getattr(cfg, "segment_eos_id", -1),
        fused_loss=getattr(cfg, "fused_lm_loss", False),
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.hidden_size,
        num_layers=cfg.num_layers,
        num_heads=cfg.num_heads,
        mlp_dim=cfg.mlp_dim,
        max_seq_len=cfg.max_seq_len,
        dropout_rate=cfg.dropout_rate,
        remat=cfg.remat,
        remat_policy=getattr(cfg, "remat_policy", "full"),
        dtype=dtype,
        param_dtype=param_dtype,
    )
