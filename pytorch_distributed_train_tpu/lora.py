"""LoRA: low-rank adaptation as a functional param-tree transform.

Parameter-efficient fine-tuning in the shape users of torch PEFT expect
(freeze the base model, train rank-r adapters on the attention/MLP
projections, merge for export), built the TPU-native way: instead of
wrapping nn.Modules and monkey-patching forward (the torch
``peft.LoraModel`` approach), the adapters live as extra leaves in the
params pytree (``.../q_proj/lora_a``, ``.../q_proj/lora_b``) and a pure
``merge`` transform folds them into the base kernels *inside the jitted
train step*:

    W_eff = stop_gradient(W) + (alpha / r) * A @ B

XLA fuses the rank-r outer product into the surrounding graph, and
``stop_gradient`` on the base lets the compiler dead-code-eliminate the
whole base-weight backward pass — the same "requires_grad=False skips the
grad kernels" effect torch gets from autograd, obtained at compile time.
The optimizer is masked with ``optax.multi_transform`` so moment buffers
exist only for adapter leaves: optimizer-state memory scales with the
adapter count, not the model (the actual point of LoRA at 7B scale, where
Adam moments are 2x params).

Reference surface replicated: the reference harness itself has no PEFT
(SURVEY [SPEC] scope), so this is a beyond-reference capability; the
config/checkpoint integration follows the same H7/H8 interfaces.

Conventions:
- ``lora_a`` is (prod(in_dims), r), initialised N(0, 1/sqrt(fan_in));
  ``lora_b`` is (r, prod(out_dims)), initialised zero — adapters start as
  an exact identity, so step 0 of a LoRA run reproduces the frozen base
  model bitwise.
- Only 2-D Dense / 3-D DenseGeneral ``kernel`` leaves whose path matches
  ``cfg.targets`` get adapters. ``cfg.extra_trainable`` names additional
  full-rank leaves to leave unfrozen (typical: norm scales or biases a la
  BitFit); a kernel matching both trains full-rank AND carries adapters.
- Weight-space LoRA has no per-call input dropout (there is no module to
  hook); classic lora_dropout=0 semantics.

All traversal uses the repo-standard ``flax.traverse_util`` flat-dict
idiom ('/'-joined paths — same convention as quant.py and optim.py's
decay masks, and as parallel/partition.py's rule regexes).
"""

from __future__ import annotations

import re
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import traverse_util


def _compile(cfg) -> tuple[re.Pattern, re.Pattern | None]:
    tgt = re.compile(cfg.targets)
    extra = re.compile(cfg.extra_trainable) if cfg.extra_trainable else None
    return tgt, extra


def _flat(tree: dict) -> dict[str, Any]:
    return traverse_util.flatten_dict(tree, sep="/")


def _unflat(flat: dict[str, Any]) -> dict:
    return traverse_util.unflatten_dict(flat, sep="/")


def _is_adapter(path: str) -> bool:
    return path.rsplit("/", 1)[-1] in ("lora_a", "lora_b")


def _split_dims(path: str, shape: tuple[int, ...], out_proj: re.Pattern
                ) -> tuple[tuple[int, ...], tuple[int, ...]]:
    """(input_dims, output_dims) of a targeted kernel. 2-D Dense is
    (in, out); 3-D DenseGeneral is (in, H, Dh) for q/k/v-style and —
    for kernels matching ``cfg.out_proj_targets`` — (H, Dh, out) with the
    contracted dims first (models/{llama,gpt2,bert,vit} convention)."""
    if len(shape) == 2:
        return shape[:1], shape[1:]
    if out_proj.search(path):
        return shape[:-1], shape[-1:]
    return shape[:1], shape[1:]


def target_paths(params: dict, cfg) -> list[str]:
    """'/'-joined paths of the kernels that receive adapters."""
    tgt, _ = _compile(cfg)
    return [
        path for path, leaf in _flat(params).items()
        if path.rsplit("/", 1)[-1] == "kernel"
        and getattr(leaf, "ndim", 0) in (2, 3)  # convs (4-D) excluded
        and tgt.search(path)
    ]


def inject(rng: jax.Array, params: dict, cfg) -> dict:
    """Return ``params`` with ``lora_a``/``lora_b`` siblings added beside
    every targeted kernel. Raises if the targets regex matches nothing —
    a silent no-op LoRA run (full model frozen, zero trainable params)
    is always a config mistake."""
    paths = target_paths(params, cfg)
    if not paths:
        raise ValueError(
            f"lora.targets={cfg.targets!r} matched no 2-D/3-D kernel in "
            "the params tree — adapter set would be empty")
    out_proj = re.compile(cfg.out_proj_targets)
    flat = dict(_flat(params))
    for i, path in enumerate(paths):
        kernel = flat[path]
        in_dims, out_dims = _split_dims(path, kernel.shape, out_proj)
        d_in = int(np.prod(in_dims))
        d_out = int(np.prod(out_dims))
        k_rng = jax.random.fold_in(rng, i)
        # A ~ N(0, 1/sqrt(d_in)) (kaiming-style fan-in), B = 0: the product
        # starts at zero so the adapted model == base model at init.
        stem = path[: -len("kernel")]
        flat[stem + "lora_a"] = (
            jax.random.normal(k_rng, (d_in, cfg.rank), jnp.float32)
            / np.sqrt(d_in)).astype(kernel.dtype)
        flat[stem + "lora_b"] = jnp.zeros((cfg.rank, d_out), kernel.dtype)
    return _unflat(flat)


def merge(params: dict, cfg, *, freeze_base: bool = True) -> dict:
    """Fold adapters into base kernels; returns a tree with the exact
    structure ``model.init`` produced (no lora keys), usable by any
    ``model.apply``. With ``freeze_base`` every leaf that is neither an
    adapter nor ``extra_trainable`` is ``stop_gradient``-ed, so
    ``jax.grad`` through the merged tree only differentiates the
    trainable set. A kernel matching both ``targets`` and
    ``extra_trainable`` keeps its gradient (full-rank + adapter)."""
    _, extra = _compile(cfg)
    scale = cfg.alpha / cfg.rank
    flat = dict(_flat(params))
    out: dict[str, Any] = {}
    for path in [p for p in flat if p.rsplit("/", 1)[-1] == "lora_a"]:
        stem = path[: -len("lora_a")]
        w = flat.pop(stem + "kernel")
        a = flat.pop(stem + "lora_a")
        b = flat.pop(stem + "lora_b")
        if freeze_base and not (extra is not None
                                and extra.search(stem + "kernel")):
            w = jax.lax.stop_gradient(w)
        delta = (a.astype(jnp.float32) @ b.astype(jnp.float32)) * scale
        out[stem + "kernel"] = w + delta.reshape(w.shape).astype(w.dtype)
    for path, leaf in flat.items():
        if freeze_base and not (extra is not None and extra.search(path)):
            leaf = jax.lax.stop_gradient(leaf)
        out[path] = leaf
    return _unflat(out)


def param_labels(params: dict, cfg) -> dict:
    """'trainable'/'frozen' label tree for ``optax.multi_transform``."""
    _, extra = _compile(cfg)
    return _unflat({
        path: ("trainable" if _is_adapter(path)
               or (extra is not None and extra.search(path)) else "frozen")
        for path in _flat(params)
    })


def mask_optimizer(tx: optax.GradientTransformation, cfg
                   ) -> optax.GradientTransformation:
    """Train adapters only. ``set_to_zero`` carries no state, so the
    wrapped optimizer allocates moments for adapter leaves alone — the
    FSDP-scale memory win that makes 7B fine-tuning fit."""
    return optax.multi_transform(
        {"trainable": tx, "frozen": optax.set_to_zero()},
        lambda params: param_labels(params, cfg),
    )


def strip(params: dict, cfg) -> dict:
    """Merge-for-export: same fold as :func:`merge` but differentiable
    nowhere needed — no stop_gradient, result has no adapter leaves.
    This is the tree to hand to generate.py / interop export."""
    return merge(params, cfg, freeze_base=False)


def transplant_base(full_params: dict, base_params: dict) -> dict:
    """Overwrite the base leaves of an adapter-injected tree with values
    from a base-only tree (warm-start from a pretrained checkpoint whose
    params predate LoRA injection). Adapter leaves keep their fresh init.
    """
    flat = dict(_flat(full_params))
    base = _flat(base_params)
    for path in flat:
        if not _is_adapter(path):
            flat[path] = base[path]
    return _unflat(flat)


def strip_abstract(params_shape: Any) -> Any:
    """Drop adapter leaves from an abstract (eval_shape) params tree —
    the restore template for a base-only checkpoint."""
    return _unflat({p: v for p, v in _flat(params_shape).items()
                    if not _is_adapter(p)})


def count_trainable(params: dict, cfg) -> tuple[int, int]:
    """(trainable, total) parameter counts — the PEFT-style banner."""
    labels = _flat(param_labels(params, cfg))
    trainable = total = 0
    for path, leaf in _flat(params).items():
        n = int(np.prod(leaf.shape)) if leaf.shape else 1
        total += n
        if labels[path] == "trainable":
            trainable += n
    return trainable, total
