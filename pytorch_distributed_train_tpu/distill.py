"""Knowledge distillation: a frozen teacher's logits guide the student.

Beyond-reference training capability (the [SPEC] harness trains from
labels only) in the classic Hinton et al. 2015 shape torch users build by
hand: total = alpha * hard_xent + (1-alpha) * T^2 * KL(teacher_T ||
student_T). Practical pairing here: distill a small llama draft from a
trained target so speculative decoding (speculative.py) gets a
high-acceptance draft — the acceptance rate is exactly what KD optimizes
(matching the target's token distributions).

TPU-native construction: the teacher forward runs INSIDE the student's
jitted train step (steps.make_train_step's ``teacher_fn`` hook) in
eval mode under the same GSPMD shardings, so teacher activations never
leave the device and XLA schedules teacher+student compute together. The
teacher's architecture is not re-specified in the student config — it is
read from the teacher checkpoint's own saved config JSON, and its
params/batch_stats restore via the same partial-restore path as the LoRA
warm start (opt_state is never read).
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp

from pytorch_distributed_train_tpu import steps as steps_lib
from pytorch_distributed_train_tpu.checkpoint import CheckpointManager


def load_teacher(distill_cfg, precision, mesh, student_loss: str):
    """Build the teacher model and restore its weights.

    Returns (model, variables) where variables = {'params', 'batch_stats'}
    ready for eval-mode apply. The teacher's ModelConfig comes from the
    config JSON the CheckpointManager stored beside the weights; its
    params are sharded by its own family's partition rules over the
    student's mesh (a 7B teacher stays sharded, not replicated)."""
    import dataclasses

    from pytorch_distributed_train_tpu.config import (
        CheckpointConfig,
        TrainConfig,
    )
    from pytorch_distributed_train_tpu.models.registry import build_model
    from pytorch_distributed_train_tpu.parallel.partition import (
        rules_for_model,
    )

    src = CheckpointManager(
        CheckpointConfig(dir=distill_cfg.teacher_checkpoint, resume="none"))
    meta = src.read_meta()
    if not meta.get("config"):
        raise FileNotFoundError(
            f"distill.teacher_checkpoint={distill_cfg.teacher_checkpoint!r}"
            " has no checkpoint with a saved config to build the teacher "
            "from")
    t_cfg = TrainConfig.from_dict(json.loads(meta["config"]))
    model_cfg = t_cfg.model
    if getattr(model_cfg, "fused_lm_loss", False):
        # The student needs (B,S,V) teacher logits; run the teacher's
        # dense head even if it trained with the fused one.
        model_cfg = dataclasses.replace(model_cfg, fused_lm_loss=False)
    model = build_model(model_cfg, precision)

    def init(rng):
        variables = model.init(
            {"params": rng},
            *steps_lib.dummy_inputs(student_loss, model_cfg, t_cfg.data),
            train=False)
        if t_cfg.lora.rank > 0:
            # A LoRA teacher's learning lives entirely in its adapter
            # leaves — the template must name them or partial_restore
            # silently skips them and we'd distill from the frozen base.
            from pytorch_distributed_train_tpu import lora as lora_lib

            variables = dict(variables)
            variables["params"] = lora_lib.inject(
                jax.random.fold_in(rng, 0x10FA), variables["params"],
                t_cfg.lora)
        return variables

    shape = jax.eval_shape(init, jax.random.PRNGKey(0))
    rules = rules_for_model(model_cfg.name)
    p_shard = rules.tree_shardings(mesh, shape["params"])
    p_abstract = jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        shape["params"], p_shard)
    # The teacher's SERVED weights: the EMA mirror when the run kept one
    # (eval/best-ckpt were measured on it — train_state.eval_params), the
    # raw params otherwise.
    step = src.latest_step()
    saved = src.saved_state_keys(step) if step is not None else None
    params_key = ("ema_params"
                  if saved is not None and "ema_params" in saved
                  else "params")
    abstract = {params_key: p_abstract}
    if shape.get("batch_stats"):
        # BN teachers (resnet) need their running stats for eval mode;
        # stats are tiny — replicate.
        from jax.sharding import NamedSharding, PartitionSpec

        rep = NamedSharding(mesh, PartitionSpec())
        abstract["batch_stats"] = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=rep),
            shape["batch_stats"])
    restored = src.restore_partial(abstract, step)
    src.close()
    if restored is None:
        raise FileNotFoundError(
            f"distill.teacher_checkpoint={distill_cfg.teacher_checkpoint!r}"
            " has no checkpoint step to restore")
    params = restored[params_key]
    if t_cfg.lora.rank > 0:
        from pytorch_distributed_train_tpu import lora as lora_lib

        params = lora_lib.strip(params, t_cfg.lora)
    variables = {"params": params}
    if "batch_stats" in restored:
        variables["batch_stats"] = restored["batch_stats"]
    return model, variables, model_cfg


def make_teacher_fn(model, variables):
    """The train-step hook: batch -> (B, ..., V) fp32 teacher logits,
    computed in eval mode with no gradient path (the KD term re-asserts
    stop_gradient). Closes over the teacher tree; under jit the arrays
    become ordinary device inputs, not baked constants."""
    batch_stats = variables.get("batch_stats", {})

    def teacher_fn(batch):
        logits, _, _ = steps_lib.apply_model(
            model, variables["params"], batch_stats, batch,
            train=False, dropout_rng=None)
        return jax.lax.stop_gradient(logits.astype(jnp.float32))

    return teacher_fn
