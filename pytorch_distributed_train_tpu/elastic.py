"""tpurun — gang launcher with restart supervision (SURVEY C10, §3.1).

The torchrun replacement. torchrun's elastic agent
(torch:distributed/run.py:985, elastic/agent/server/api.py:455) spawns one
worker per device, rendezvouses them through a TCPStore, monitors, and
restarts failed workers in place. Under SPMD a single surviving rank is
useless — the correct unit of restart is the WHOLE gang, resuming from the
latest checkpoint (SURVEY §5.3b: ``checkpoint.resume='auto'`` is the default
path). So this agent:

1. hosts the native rendezvous store (native/store.cpp — the TCPStore
   analogue) and publishes its address to workers via ``TPUSTORE_ADDR``;
2. spawns ``nprocs`` workers with the env contract
   ``PROCESS_ID / NUM_PROCESSES / COORDINATOR_ADDRESS`` (consumed by
   launch.initialize_distributed → jax.distributed.initialize);
3. monitors the gang; on any worker death it kills the rest, bumps the
   restart generation in the store, and respawns everyone — up to
   ``max_restarts`` times (elastic agent semantics, whole-gang flavor);
4. exits 0 only when every worker exits 0.

With ``--min-nnodes`` the world is DYNAMIC (torchrun's min/max-nnodes,
torch:distributed/elastic/rendezvous/dynamic_rendezvous.py:1148): each
restart generation rendezvouses whichever node agents survive, and once
the window passes proceeds with >= min_nnodes of them — NUM_PROCESSES
shrinks, node indices re-densify, workers rebuild the mesh from the new
device count and resume from the latest Orbax checkpoint
(reshard-on-restore), keeping the configured GLOBAL batch intact.

Workers can use ``worker_store()`` for launcher-mediated KV exchange and
barriers (the same role c10d's store plays for init handshakes).
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import socket
import subprocess
import sys
import time


@dataclasses.dataclass
class LaunchConfig:
    nprocs: int
    max_restarts: int = 3
    monitor_interval_s: float = 0.5
    # Multi-host: total processes = nnodes * nprocs; this host contributes
    # ranks [node_rank*nprocs, (node_rank+1)*nprocs). Node 0 hosts the store
    # and the JAX coordinator.
    nnodes: int = 1
    node_rank: int = 0
    master_addr: str = "127.0.0.1"
    store_port: int = 0  # 0 → ephemeral (single-node only)
    env: dict | None = None
    # Dynamic membership (torchrun's min/max-nnodes semantics,
    # torch:distributed/elastic/rendezvous/dynamic_rendezvous.py:1148):
    # 0 → the world is FIXED at nnodes (default; a lost node means the
    # job retries until the scheduler replaces it). >0 → each restart
    # generation rendezvouses whoever shows up within
    # ``rendezvous_window_s`` and proceeds DEGRADED once >= min_nnodes
    # nodes arrived: NUM_PROCESSES shrinks, workers rebuild the mesh
    # from the surviving device count, and training resumes from the
    # latest checkpoint via reshard-on-restore (the global batch stays
    # constant — data.local_batch_size divides by process_count). Node 0
    # must survive: it hosts the store + JAX coordinator.
    min_nnodes: int = 0
    rendezvous_window_s: float = 10.0
    # Worker shutdown escalation: SIGTERM, then SIGKILL once this grace
    # period expires. A worker wedged in a collective (or one taking a
    # graceful-preemption checkpoint that outruns the grace) cannot
    # ignore its way into wedging the gang restart — SIGKILL is
    # unconditional. Size it to cover a checkpoint save when workers run
    # with faults.graceful_preemption.
    shutdown_grace_s: float = 10.0
    # Hard ceiling on a rendezvous round: below min_nnodes arrivals when
    # it expires → the round FAILS (rc 44) instead of spinning forever
    # (matches the fixed-world barrier's 600 s bound).
    rendezvous_timeout_s: float = 600.0
    # WINDOWED restart budget (torchrun counts restarts absolutely; a
    # long job then dies on its Nth transient fault even with days of
    # healthy running between them, while a crash-looping job burns the
    # whole budget in seconds). Here a generation that ran at least
    # ``stable_window_s`` before failing RESETS ``restarts_used`` — the
    # budget meters crash LOOPS, not lifetime bad luck — and each
    # respawn backs off exponentially (base * 2^k, capped, +jitter so a
    # multi-node gang's agents don't respawn in lockstep against a
    # shared resource).
    stable_window_s: float = 300.0
    backoff_base_s: float = 1.0
    backoff_max_s: float = 30.0
    backoff_jitter: float = 0.25
    # Persistent XLA compile cache base dir: each worker gets its OWN
    # subdirectory (<base>/worker_<rank>, via PDTT_COMPILE_CACHE_DIR).
    # This container's jax loads truncated cache entries without
    # validation, so a worker killed mid-cache-write (crash drill,
    # SIGKILL escalation) sharing one dir would poison its siblings and
    # every later generation (CHANGES PR 3). Worker rank is stable
    # across generations, so each worker still reuses its own entries.
    compile_cache_base: str = ""
    # Shared event-journal directory (obs/events.py): the agent journals
    # spawn/failure/restart events there and exports it to workers as
    # PDTT_EVENTS_DIR, so tools/timeline_report.py merges the launcher's
    # view of an outage with every host's. "" = agent does not journal
    # (workers still default to <checkpoint.dir>/events).
    events_dir: str = ""


def worker_cache_dir(base: str, rank) -> str:
    """Per-worker compile-cache subdir — one writer per directory, so a
    mid-write kill can only ever poison the killed worker's own cache."""
    return os.path.join(base, f"worker_{rank}")


# Store keys of the elastic world plane (docs/elastic.md): the agent
# publishes each generation's membership and the job's maximum world so
# workers can reshard data/checkpoints without parsing launcher logs.
WORLD_KEY_PREFIX = "elastic/world/"
WORLD_MAX_KEY = "elastic/world_max"


def elastic_world() -> tuple[int, int]:
    """(world, rank) of THIS restart generation from the launcher env
    contract (``NUM_PROCESSES`` / ``PROCESS_ID``); (1, 0) outside
    tpurun (both vars absent). This is the worker-side source of truth
    for elastic data sharding (``data.elastic_shards``): a degraded
    generation's env already carries the SHRUNKEN world and the
    re-densified rank.

    A PRESENT but inconsistent contract (non-numeric, or a stale rank
    outside [0, world)) raises: silently treating it as a 1-host world
    would make this host train on the FULL dataset and global batch
    while its peers shard theirs — duplicated records and a skewed
    effective batch with no error anywhere."""
    w = os.environ.get("NUM_PROCESSES")
    r = os.environ.get("PROCESS_ID")
    if w is None and r is None:
        return 1, 0
    if w is None or r is None:
        # Half a contract is no contract: defaulting the missing var
        # would silently put every host on rank 0 (or world 1).
        raise RuntimeError(
            f"corrupt launcher env contract: NUM_PROCESSES={w!r} "
            f"PROCESS_ID={r!r} must be set together")
    try:
        world = int(w)
        rank = int(r)
    except ValueError as e:
        raise RuntimeError(
            f"corrupt launcher env contract: NUM_PROCESSES={w!r} "
            f"PROCESS_ID={r!r} must both be integers") from e
    if world < 1 or not 0 <= rank < world:
        raise RuntimeError(
            f"corrupt launcher env contract: PROCESS_ID={rank} outside "
            f"[0, NUM_PROCESSES={world}) — a stale env from an earlier "
            "generation?")
    return world, rank


def store_world_max(store, default: int = 0) -> int:
    """The job's gen-0 world size, read back from the launcher store
    (``WORLD_MAX_KEY``), or ``default`` when absent/unreachable. Host
    ids are dense, so ``range(store_world_max(...))`` enumerates every
    rank that could EVER have published a peer snapshot — including
    ranks lost to a shrink."""
    if store is None:
        return default
    try:
        return max(default, int(store.get(WORLD_MAX_KEY,
                                          timeout_ms=50).decode()))
    except Exception:
        return default


def store_world(store, gen: int) -> dict | None:
    """The membership record the agent published for generation ``gen``
    (``_publish_world``), or None."""
    if store is None:
        return None
    try:
        return json.loads(store.get(f"{WORLD_KEY_PREFIX}{int(gen)}",
                                    timeout_ms=50).decode())
    except Exception:
        return None


# Serving replica registry on the same store (docs/serving_reliability
# .md): each ``serve_http --advertise`` process claims the next index
# and publishes its address; the router enumerates the counter and
# probes whatever it finds. Dead entries are fine — a replica that
# restarts claims a NEW index and the router's health prober marks the
# stale address down; the registry is a discovery hint, /healthz is
# the truth.
SERVE_REPLICA_COUNT_KEY = "serve/replicas_n"
SERVE_REPLICA_KEY_PREFIX = "serve/replica/"
# a cleanly-exited replica overwrites its record with this sentinel:
# crashed replicas still leave their address behind (the prober handles
# those), but a deliberate drain/exit must not leave a forever-probed
# ghost — a controller counting fleet size from the registry would
# over-count dead replicas and its scale-in math would drift after
# every recycle
SERVE_REPLICA_TOMBSTONE = b"__tombstone__"


def publish_replica(store, addr: str) -> int:
    """Register a serving replica's ``host:port`` with the launcher
    store; returns its registry index."""
    idx = int(store.add(SERVE_REPLICA_COUNT_KEY, 1)) - 1
    store.set(f"{SERVE_REPLICA_KEY_PREFIX}{idx}", addr.encode())
    return idx


def tombstone_replica(store, idx: int) -> bool:
    """Mark a registry slot dead on clean exit (serve_http's drain /
    shutdown path). Best-effort: a crash simply leaves the address
    behind, same as before tombstones existed."""
    if store is None or idx < 0:
        return False
    try:
        store.set(f"{SERVE_REPLICA_KEY_PREFIX}{int(idx)}",
                  SERVE_REPLICA_TOMBSTONE)
        return True
    except Exception:
        return False


def discover_replicas(store, strict: bool = False) -> list[str]:
    """Every address ever advertised and not tombstoned (order =
    registration order; the prober, not this list, decides liveness of
    what remains). Empty when nothing registered or the store is
    unreachable.

    A MISSING index — a publisher that crashed between ``add(COUNT)``
    and ``set(key_<idx>)`` left a counter-covered hole — is skippable
    forever, exactly like a corrupt record: the key-absent TimeoutError
    is an ANSWER from a healthy store. Transport failures are not:
    under ``strict=True`` they re-raise (OSError) instead of degrading
    into a silently-partial/empty list, so a resilient caller
    (store_plane.ResilientStore.cached) can tell "registry is empty"
    from "store is down" and serve its last-known-good answer."""
    if store is None:
        return []
    try:
        # the counter is an add() key (raw int64 on the wire): a
        # zero-delta add reads it back — and creates 0 when absent
        n = int(store.add(SERVE_REPLICA_COUNT_KEY, 0))
    except Exception:
        if strict:
            raise
        return []
    out: list[str] = []
    for i in range(n):
        try:
            raw = store.get(f"{SERVE_REPLICA_KEY_PREFIX}{i}",
                            timeout_ms=200)
        except TimeoutError:
            continue  # partial-publish hole: skippable, forever
        except Exception:
            if strict:
                raise
            continue  # transport trouble: legacy best-effort skip
        if raw == SERVE_REPLICA_TOMBSTONE:
            continue  # cleanly exited: not a discovery candidate
        out.append(raw.decode())
    return out


# Observability-endpoint registry on the same store (docs/observability
# .md "Fleet health plane"): the symmetric twin of the serving-replica
# registry above, but for SCRAPE surfaces — the trainer metrics sidecar
# and serve_http self-register {role, addr, host, gen} so the fleet
# collector (obs/collector.py) discovers every /metrics + /healthz
# target without static config. Same liveness stance as replicas: dead
# records are fine, the collector's staleness tracking (not this list)
# decides who is alive; a restarted process claims a NEW index.
OBS_ENDPOINT_COUNT_KEY = "obs/endpoints_n"
OBS_ENDPOINT_KEY_PREFIX = "obs/endpoint/"


def publish_obs_endpoint(store, role: str, addr: str,
                         host: str | None = None,
                         gen: str | None = None) -> int:
    """Register a scrape endpoint (``role`` in {"trainer", "serving"},
    ``addr`` a routable ``host:port`` whose /metrics answers) with the
    launcher store; returns its registry index. ``host``/``gen``
    default to the launcher env contract identity — the same writer id
    the event journal uses, so fleet state and journals cross-link.
    OUTSIDE the env contract (no PROCESS_ID: ad-hoc replicas) the addr
    itself is the host identity — the collector keys targets by
    (role, host), and N replicas all defaulting to "host0" would
    silently collapse into one target with N-1 of them never
    scraped."""
    pid = os.environ.get("PROCESS_ID")
    rec = {
        "role": role, "addr": addr,
        "host": host if host is not None else (
            f"host{pid}" if pid is not None else addr),
        "gen": gen if gen is not None else os.environ.get(
            "RESTART_GENERATION", "0"),
        "pid": os.getpid(),
    }
    idx = int(store.add(OBS_ENDPOINT_COUNT_KEY, 1)) - 1
    store.set(f"{OBS_ENDPOINT_KEY_PREFIX}{idx}",
              json.dumps(rec, sort_keys=True).encode())
    return idx


def discover_obs_endpoints(store, strict: bool = False) -> list[dict]:
    """Every endpoint record ever published (registration order), each
    carrying its registry ``idx``. Corrupt/unlanded records are skipped;
    empty when nothing registered or the store is unreachable.

    Same hole/strict contract as :func:`discover_replicas`: a missing
    index (publisher crashed between the counter add and the record
    set) is a skippable hole; under ``strict=True`` transport failures
    re-raise instead of truncating the registry."""
    if store is None:
        return []
    try:
        n = int(store.add(OBS_ENDPOINT_COUNT_KEY, 0))
    except Exception:
        if strict:
            raise
        return []
    out: list[dict] = []
    for i in range(n):
        try:
            raw = store.get(f"{OBS_ENDPOINT_KEY_PREFIX}{i}",
                            timeout_ms=200)
        except TimeoutError:
            continue  # partial-publish hole: skippable, forever
        except Exception:
            if strict:
                raise
            continue  # transport trouble: legacy best-effort skip
        try:
            rec = json.loads(raw.decode())
        except ValueError:
            continue  # corrupt record: skippable like a hole
        if not isinstance(rec, dict) or "addr" not in rec:
            continue
        rec["idx"] = i
        out.append(rec)
    return out


def routable_host(bind_host: str) -> str:
    """A peer-connectable address for a locally-bound server: wildcard
    binds advertise the host's resolved name instead (the serve_http
    --advertise rule, shared with the obs-endpoint publishers)."""
    if bind_host not in ("", "0.0.0.0", "::"):
        return bind_host
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return socket.gethostname()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _backoff_delay(consecutive_failures: int, base_s: float, max_s: float,
                   jitter: float, rand=None) -> float:
    """Respawn delay before restart attempt k (1-based): base * 2^(k-1),
    capped at max, stretched by up to ``jitter`` fraction of itself
    (uniform). Pure so tests can pin it."""
    import random as _random

    rand = rand if rand is not None else _random.random
    delay = min(base_s * (2 ** max(consecutive_failures - 1, 0)), max_s)
    return delay * (1.0 + jitter * rand())


class ElasticAgent:
    def __init__(self, cfg: LaunchConfig, cmd: list[str]):
        self.cfg = cfg
        self.cmd = cmd
        self.server = None
        self.store_port = cfg.store_port
        self.coord_port = None
        self.procs: list[subprocess.Popen] = []
        self.agent_client = None  # agent↔agent coordination (nnodes > 1)
        self._world_nodes = cfg.nnodes  # current generation's node count
        self._members = list(range(cfg.nnodes))  # original ranks, this gen
        self._last_gen = 0

    # ------------------------------------------------------------ lifecycle
    def _start_store(self) -> None:
        if self.cfg.node_rank == 0:
            from pytorch_distributed_train_tpu.native.store import StoreServer

            self.server = StoreServer(self.cfg.store_port)
            self.store_port = self.server.port
            self.coord_port = _free_port()
            # Publish the JAX coordinator endpoint for every node's workers.
            from pytorch_distributed_train_tpu.native.store import StoreClient

            with StoreClient("127.0.0.1", self.store_port) as c:
                c.set("coord", f"{self.cfg.master_addr}:{self.coord_port}"
                      .encode())
                # The job's MAXIMUM world (gen-0 size): host ids are
                # dense in [0, world_max), so a restoring worker after a
                # shrink can still enumerate peer-store snapshots that
                # were published under the OLD (larger) world's ranks —
                # ckpt/peer.py reads this through store_world_max().
                c.set(WORLD_MAX_KEY,
                      str(self.cfg.nnodes * self.cfg.nprocs).encode())
        else:
            from pytorch_distributed_train_tpu.native.store import StoreClient

            with StoreClient(self.cfg.master_addr, self.store_port,
                             timeout_ms=120_000) as c:
                coord = c.get("coord", timeout_ms=120_000).decode()
            self.coord_port = int(coord.rsplit(":", 1)[1])

    def _spawn(self, restart_gen: int, world_nodes: int | None = None,
               node_index: int | None = None) -> None:
        cfg = self.cfg
        if world_nodes is None:
            world_nodes = cfg.nnodes
        if node_index is None:
            node_index = cfg.node_rank
        world = world_nodes * cfg.nprocs
        self.procs = []
        for local in range(cfg.nprocs):
            rank = node_index * cfg.nprocs + local
            env = dict(os.environ)
            env.update(cfg.env or {})
            env.update({
                "PROCESS_ID": str(rank),
                "LOCAL_PROCESS_ID": str(local),
                "NUM_PROCESSES": str(world),
                "COORDINATOR_ADDRESS":
                    f"{cfg.master_addr}:{self.coord_port}",
                "TPUSTORE_ADDR": f"{cfg.master_addr}:{self.store_port}",
                "RESTART_GENERATION": str(restart_gen),
            })
            if cfg.compile_cache_base:
                env["PDTT_COMPILE_CACHE_DIR"] = worker_cache_dir(
                    cfg.compile_cache_base, rank)
            if cfg.events_dir:
                env["PDTT_EVENTS_DIR"] = cfg.events_dir
            self.procs.append(subprocess.Popen(self.cmd, env=env))
        self._emit("spawn", gen=restart_gen, world=world,
                   nprocs=cfg.nprocs)
        self._log(f"spawned {cfg.nprocs} workers (gen {restart_gen}, "
                  f"world {world}, coord :{self.coord_port})")

    def _publish_world(self, rnd: int, members: list[int],
                       nprocs: int) -> None:
        """Node 0: publish this generation's world to the store
        (``elastic/world/<gen>``) BEFORE spawning it — elastic
        resharding's contract that workers (and post-mortem tools) can
        read what the gang believed the world was, per generation,
        without parsing launcher logs. Best-effort: supervision never
        dies of a store hiccup."""
        if self.cfg.node_rank != 0:
            return
        try:
            from pytorch_distributed_train_tpu.native.store import (
                StoreClient,
            )

            c = self.agent_client
            transient = c is None
            if transient:  # single-node job: no agent↔agent client
                c = StoreClient("127.0.0.1", self.store_port)
            c.set(f"{WORLD_KEY_PREFIX}{rnd}", json.dumps(
                {"gen": rnd, "nodes": len(members), "nprocs": nprocs,
                 "world": len(members) * nprocs,
                 "members": list(members)}, sort_keys=True).encode())
            if transient:
                c.close()
        except Exception:
            pass

    def _kill_all(self) -> None:
        """SIGTERM every live worker, then escalate to SIGKILL for any
        still alive when ``shutdown_grace_s`` expires (torchrun's
        SignalException escalation): a worker stuck in a collective —
        or one that installed a SIGTERM handler and wedged inside it —
        must not be able to stall the gang restart indefinitely."""
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + self.cfg.shutdown_grace_s
        for p in self.procs:
            try:
                p.wait(max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                self._log(f"worker pid {p.pid} survived SIGTERM past the "
                          f"{self.cfg.shutdown_grace_s:.1f}s grace; "
                          "escalating to SIGKILL")
                p.kill()
                p.wait()

    def _log(self, msg: str) -> None:
        print(f"[tpurun] {msg}", flush=True)

    def _emit(self, name: str, **detail) -> None:
        """Journal one launcher event (category ``elastic``) — no-op
        unless ``events_dir`` was configured. Best-effort: supervision
        must never die of a full disk."""
        if not self.cfg.events_dir:
            return
        try:
            from pytorch_distributed_train_tpu.obs import events as evl

            evl.emit("elastic", name, **detail)
        except Exception:
            pass

    # ---------------------------------------------------------------- run
    def run(self) -> int:
        cfg = self.cfg
        if cfg.events_dir:
            from pytorch_distributed_train_tpu.obs import events as evl

            evl.configure(cfg.events_dir, who=f"agent{cfg.node_rank}")
        self._start_store()
        try:
            if cfg.nnodes > 1:
                from pytorch_distributed_train_tpu.native.store import (
                    StoreClient,
                )

                host = "127.0.0.1" if cfg.node_rank == 0 else cfg.master_addr
                self.agent_client = StoreClient(host, self.store_port,
                                                timeout_ms=120_000)
            rnd = 0  # rendezvous round == RESTART_GENERATION (store-global)
            restarts_used = 0
            while True:
                members = list(range(cfg.nnodes))
                node_index = cfg.node_rank
                if self.agent_client is not None:
                    if cfg.min_nnodes > 0:
                        try:
                            rnd, members, node_index = \
                                self._rendezvous_round(rnd)
                        except (TimeoutError, OSError) as e:
                            # TimeoutError: the round never filled (node 0),
                            # no further round opened for a waiting-excluded
                            # node, or the world key never appeared.
                            # OSError: the store died under us — node 0
                            # tears it down when ITS round fails, and a
                            # surviving peer's blocked get comes back as a
                            # connection error; same condition, not a crash.
                            self._log(f"rendezvous failed: "
                                      f"{type(e).__name__}: {e}")
                            return 44
                    else:
                        # Gang restarts are whole-JOB: every node's agent
                        # meets here before (re)spawning, no generation
                        # skew. The barrier key syncs through the same
                        # store-global round as the dynamic path — an
                        # agent relaunched mid-job must not sit on
                        # barrier/0 while peers wait on barrier/k.
                        if cfg.node_rank == 0:
                            self.agent_client.set("rdzv/open",
                                                  str(rnd).encode())
                        else:
                            cur = int(self.agent_client.get(
                                "rdzv/open", timeout_ms=600_000).decode())
                            rnd = max(rnd, cur)
                        self.agent_client.barrier(
                            f"agents/spawn/{rnd}", cfg.nnodes, cfg.node_rank,
                            timeout_ms=600_000)
                self._last_gen = rnd
                self._world_nodes = len(members)
                self._members = members
                self._publish_world(rnd, members, cfg.nprocs)
                if len(members) != cfg.nnodes:
                    self._emit("reshard", gen=rnd, nodes=len(members),
                               of=cfg.nnodes,
                               world=len(members) * cfg.nprocs)
                t_spawn = time.monotonic()
                self._spawn(rnd, len(members), node_index)
                rc = self._monitor(rnd)
                if rc == 0:
                    self._emit("done", gen=rnd)
                    self._log("all workers exited cleanly")
                    return 0
                self._emit("worker_failed", gen=rnd, rc=rc)
                ran_s = time.monotonic() - t_spawn
                if ran_s >= cfg.stable_window_s and restarts_used:
                    # Windowed budget: this generation ran long enough to
                    # count as healthy — the failure is fresh bad luck,
                    # not a continuation of a crash loop.
                    self._log(f"generation ran {ran_s:.1f}s >= stable "
                              f"window {cfg.stable_window_s:.1f}s; "
                              f"resetting restart budget "
                              f"({restarts_used} used)")
                    restarts_used = 0
                if restarts_used >= cfg.max_restarts:
                    self._emit("budget_exhausted", rc=rc,
                               restarts=restarts_used)
                    self._log(f"worker failed (rc={rc}); restart budget "
                              f"exhausted after {restarts_used} restarts")
                    return rc
                restarts_used += 1
                rnd += 1
                delay = _backoff_delay(restarts_used, cfg.backoff_base_s,
                                       cfg.backoff_max_s,
                                       cfg.backoff_jitter)
                self._emit("restart", gen=rnd, rc=rc,
                           restarts=restarts_used,
                           delay_s=round(delay, 2))
                self._log(f"worker failed (rc={rc}); restarting gang "
                          f"({restarts_used}/{cfg.max_restarts}) after "
                          f"{delay:.2f}s backoff")
                time.sleep(delay)
        finally:
            if self.agent_client is not None:
                # Node 0 hosts the store every other agent is still polling:
                # it must leave LAST. Non-host agents drop a per-rank exit
                # flag and go; node 0 waits for every member of the final
                # generation (per-rank + per-gen keys, so a node that died
                # or exited in an EARLIER generation can't release node 0
                # before a still-monitoring survivor is done — a stale
                # arrival on a shared barrier did exactly that). A dead
                # peer can't wedge shutdown: the waits share one deadline
                # and timeouts are swallowed.
                try:
                    if self.cfg.node_rank == 0:
                        deadline = time.monotonic() + 60.0
                        for r in self._members:
                            if r == 0:
                                continue
                            left_ms = max(1, int(
                                (deadline - time.monotonic()) * 1000))
                            try:
                                self.agent_client.wait(
                                    f"agents/exit/{self._last_gen}/{r}",
                                    timeout_ms=left_ms)
                            except TimeoutError:
                                pass
                    else:
                        self.agent_client.set(
                            f"agents/exit/{self._last_gen}/"
                            f"{self.cfg.node_rank}", b"1")
                except Exception:
                    pass  # a dead peer must not wedge shutdown
                self.agent_client.close()
            if self.server is not None:
                self.server.stop()

    def _rendezvous_round(self, rnd: int) -> tuple[int, list[int], int]:
        """Dynamic-membership rendezvous; returns (round, members, index).

        The degraded-restart path (SURVEY C11;
        torch:...dynamic_rendezvous.py:1148 rendezvouses [min, max] nodes
        the same way): every surviving agent registers; node 0 closes the
        round when all ``nnodes`` arrived, or — once
        ``rendezvous_window_s`` has passed — when at least ``min_nnodes``
        did. Members get DENSE new node indices in node_rank order, so
        ranks stay contiguous for the shrunken world.

        Rounds are STORE-GLOBAL, not loop-local: node 0 publishes the
        round it is opening under ``rdzv/open``, and every other agent
        syncs to ``max(local, open)`` before registering — so an agent
        relaunched by the scheduler (fresh process, local round 0) joins
        the job's CURRENT round instead of replaying a stale one's world
        key with the original NUM_PROCESSES. A node that arrives after a
        round closed doesn't exit: it pre-registers for the NEXT round and
        blocks until node 0 opens it (on the next gang restart) — the
        torchrun late-joiner behavior. Raises TimeoutError when a round
        never fills (node 0) or no joinable round appears within
        ``rendezvous_timeout_s`` (waiting node: the job likely finished).
        """
        c = self.agent_client
        cfg = self.cfg
        if cfg.node_rank == 0:
            c.set("rdzv/open", str(rnd).encode())
            c.set(f"rdzv/{rnd}/member/0", b"1")
            c.add(f"rdzv/{rnd}/count", 1)
            members = self._close_round(rnd)
            return rnd, members, members.index(0)
        deadline = time.monotonic() + cfg.rendezvous_timeout_s
        while True:
            left_ms = max(1, int((deadline - time.monotonic()) * 1000))
            cur = int(c.get("rdzv/open", timeout_ms=left_ms).decode())
            rnd = max(rnd, cur)
            c.set(f"rdzv/{rnd}/member/{cfg.node_rank}", b"1")
            c.add(f"rdzv/{rnd}/count", 1)
            left_ms = max(1, int((deadline - time.monotonic()) * 1000))
            try:
                raw = c.get(f"rdzv/{rnd}/world", timeout_ms=left_ms).decode()
            except TimeoutError:
                # Leaving without un-registering would poison the round:
                # when a later failure finally opens it, the world would
                # include this long-gone node and the gang would hang
                # waiting for its ranks. (A close racing this cleanup can
                # still publish us — narrow window, bounded by the
                # monitor's failure path.)
                try:
                    c.delete(f"rdzv/{rnd}/member/{cfg.node_rank}")
                    c.add(f"rdzv/{rnd}/count", -1)
                except Exception:
                    pass
                raise
            members = [int(r) for r in raw.split(",") if r]
            if cfg.node_rank in members:
                return rnd, members, members.index(cfg.node_rank)
            self._log(f"excluded from round {rnd} (arrived after it "
                      "closed); pre-registering for the next round")
            rnd += 1
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"no joinable round within "
                    f"{cfg.rendezvous_timeout_s:.0f}s (last tried {rnd})")

    def _close_round(self, rnd: int) -> list[int]:
        """Node 0: wait out the round's window, then publish the member
        list (the world) for generation ``rnd``."""
        c = self.agent_client
        cfg = self.cfg
        deadline = time.monotonic() + cfg.rendezvous_window_s
        hard_deadline = time.monotonic() + cfg.rendezvous_timeout_s
        while True:
            n = c.add(f"rdzv/{rnd}/count", 0)
            if n >= cfg.nnodes:
                break
            if n >= max(cfg.min_nnodes, 1) and time.monotonic() >= deadline:
                self._emit("rendezvous_degraded", gen=rnd, nodes=n,
                           of=cfg.nnodes)
                self._log(f"rendezvous round {rnd}: window closed with "
                          f"{n}/{cfg.nnodes} nodes — proceeding degraded")
                break
            if time.monotonic() >= hard_deadline:
                raise TimeoutError(
                    f"rendezvous round {rnd}: only {n} of min "
                    f"{max(cfg.min_nnodes, 1)} nodes arrived within "
                    f"{cfg.rendezvous_timeout_s:.0f}s")
            time.sleep(0.1)
        # Enumerate members. Every registrant set() its member key BEFORE
        # add()ing the count, so >= n keys exist by now — keep sweeping
        # until we've found at least n (a too-short probe could drop an
        # already-counted node on a loaded host, ejecting a healthy member
        # and shrinking the gang below the count that closed the round).
        n_final = c.add(f"rdzv/{rnd}/count", 0)
        members: list[int] = []
        sweep_deadline = time.monotonic() + 30.0
        while True:
            members = []
            for r in range(cfg.nnodes):
                try:
                    c.get(f"rdzv/{rnd}/member/{r}", timeout_ms=50)
                    members.append(r)
                except TimeoutError:
                    pass
            if len(members) >= n_final or time.monotonic() >= sweep_deadline:
                break
            time.sleep(0.05)
        c.set(f"rdzv/{rnd}/world", ",".join(map(str, members)).encode())
        return members

    def _peer_failure(self, gen: int) -> int | None:
        """rc another node published for this generation, or None."""
        if self.agent_client is None:
            return None
        try:
            return int(self.agent_client.get(f"gang/fail/{gen}", timeout_ms=1))
        except TimeoutError:
            return None

    def _monitor(self, gen: int) -> int:
        """Waits for gang completion. Returns 0 (all nodes clean) or the
        first bad rc — publishing local failures to peer agents so every
        node restarts together (SPMD: the unit of restart is the job)."""
        local_done = False
        while True:
            time.sleep(self.cfg.monitor_interval_s)
            rc = self._peer_failure(gen)
            if rc is not None:
                self._kill_all()
                return rc
            if not local_done:
                codes = [p.poll() for p in self.procs]
                bad = [c for c in codes if c not in (None, 0)]
                if bad:
                    if self.agent_client is not None:
                        self.agent_client.set(f"gang/fail/{gen}",
                                              str(bad[0]).encode())
                    self._kill_all()
                    return bad[0]
                if all(c == 0 for c in codes):
                    if self.agent_client is None:
                        return 0
                    local_done = True
                    n = self.agent_client.add(f"gang/ok/{gen}", 1)
                    if n == self._world_nodes:
                        self.agent_client.set(f"gang/alldone/{gen}", b"1")
            else:
                try:
                    self.agent_client.wait(f"gang/alldone/{gen}", timeout_ms=1)
                    return 0
                except TimeoutError:
                    pass  # peers still running; keep watching for failures


def worker_store():
    """Connect to the launcher's store from inside a worker (or None when
    not running under tpurun)."""
    addr = os.environ.get("TPUSTORE_ADDR")
    if not addr:
        return None
    from pytorch_distributed_train_tpu.native.store import StoreClient

    host, port = addr.rsplit(":", 1)
    return StoreClient(host, int(port))


def main(argv: list[str] | None = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="tpurun",
        description="Gang launcher with whole-job restart supervision "
                    "(the torchrun analogue).",
    )
    p.add_argument("--nprocs", type=int, required=True,
                   help="worker processes on this node")
    p.add_argument("--max-restarts", type=int, default=3)
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--node-rank", type=int, default=0)
    p.add_argument("--master-addr", default="127.0.0.1")
    p.add_argument("--store-port", type=int, default=0,
                   help="required (nonzero) when nnodes > 1")
    p.add_argument("--min-nnodes", type=int, default=0,
                   help="degraded-restart floor: restart generations "
                        "proceed with >= this many nodes once the "
                        "rendezvous window passes (0 = fixed world; "
                        "torchrun's min/max-nnodes analogue)")
    p.add_argument("--rendezvous-window", type=float, default=10.0,
                   help="seconds node 0 waits for stragglers before "
                        "closing a degraded rendezvous round")
    p.add_argument("--monitor-interval", type=float, default=0.5)
    p.add_argument("--shutdown-grace", type=float, default=10.0,
                   help="seconds between SIGTERM and SIGKILL when tearing "
                        "down workers (raise it when workers checkpoint "
                        "on SIGTERM — faults.graceful_preemption)")
    p.add_argument("--stable-window", type=float, default=300.0,
                   help="a generation that runs at least this long before "
                        "failing resets the restart budget (the budget "
                        "meters crash LOOPS, not lifetime restarts)")
    p.add_argument("--backoff-base", type=float, default=1.0,
                   help="respawn backoff: base seconds, doubling per "
                        "consecutive fast failure")
    p.add_argument("--backoff-max", type=float, default=30.0,
                   help="respawn backoff cap in seconds")
    p.add_argument("--compile-cache-dir", default="",
                   help="persistent XLA compile cache BASE dir; each "
                        "worker gets <base>/worker_<rank> so a killed "
                        "worker's truncated cache entry cannot poison "
                        "siblings or later generations")
    p.add_argument("--events-dir", default="",
                   help="shared event-journal directory (obs/events.py): "
                        "the agent journals spawn/failure/restart events "
                        "there and workers inherit it via PDTT_EVENTS_DIR "
                        "— one directory, every process's timeline")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="worker command, e.g. train.py --config ...")
    args = p.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        p.error("missing worker command")
    if args.nnodes > 1 and args.store_port == 0:
        p.error("--store-port must be fixed when nnodes > 1")
    if cmd[0].endswith(".py"):
        cmd = [sys.executable] + cmd
    if args.min_nnodes > args.nnodes:
        p.error("--min-nnodes cannot exceed --nnodes")
    cfg = LaunchConfig(
        nprocs=args.nprocs, max_restarts=args.max_restarts,
        nnodes=args.nnodes, node_rank=args.node_rank,
        master_addr=args.master_addr, store_port=args.store_port,
        monitor_interval_s=args.monitor_interval,
        min_nnodes=args.min_nnodes,
        rendezvous_window_s=args.rendezvous_window,
        shutdown_grace_s=args.shutdown_grace,
        stable_window_s=args.stable_window,
        backoff_base_s=args.backoff_base,
        backoff_max_s=args.backoff_max,
        compile_cache_base=args.compile_cache_dir,
        events_dir=args.events_dir,
    )
    return ElasticAgent(cfg, cmd).run()


if __name__ == "__main__":
    sys.exit(main())
