"""tpurun — gang launcher with restart supervision (SURVEY C10, §3.1).

The torchrun replacement. torchrun's elastic agent
(torch:distributed/run.py:985, elastic/agent/server/api.py:455) spawns one
worker per device, rendezvouses them through a TCPStore, monitors, and
restarts failed workers in place. Under SPMD a single surviving rank is
useless — the correct unit of restart is the WHOLE gang, resuming from the
latest checkpoint (SURVEY §5.3b: ``checkpoint.resume='auto'`` is the default
path). So this agent:

1. hosts the native rendezvous store (native/store.cpp — the TCPStore
   analogue) and publishes its address to workers via ``TPUSTORE_ADDR``;
2. spawns ``nprocs`` workers with the env contract
   ``PROCESS_ID / NUM_PROCESSES / COORDINATOR_ADDRESS`` (consumed by
   launch.initialize_distributed → jax.distributed.initialize);
3. monitors the gang; on any worker death it kills the rest, bumps the
   restart generation in the store, and respawns everyone — up to
   ``max_restarts`` times (elastic agent semantics, whole-gang flavor);
4. exits 0 only when every worker exits 0.

Workers can use ``worker_store()`` for launcher-mediated KV exchange and
barriers (the same role c10d's store plays for init handshakes).
"""

from __future__ import annotations

import dataclasses
import os
import signal
import socket
import subprocess
import sys
import time


@dataclasses.dataclass
class LaunchConfig:
    nprocs: int
    max_restarts: int = 3
    monitor_interval_s: float = 0.5
    # Multi-host: total processes = nnodes * nprocs; this host contributes
    # ranks [node_rank*nprocs, (node_rank+1)*nprocs). Node 0 hosts the store
    # and the JAX coordinator.
    nnodes: int = 1
    node_rank: int = 0
    master_addr: str = "127.0.0.1"
    store_port: int = 0  # 0 → ephemeral (single-node only)
    env: dict | None = None


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


class ElasticAgent:
    def __init__(self, cfg: LaunchConfig, cmd: list[str]):
        self.cfg = cfg
        self.cmd = cmd
        self.server = None
        self.store_port = cfg.store_port
        self.coord_port = None
        self.procs: list[subprocess.Popen] = []
        self.agent_client = None  # agent↔agent coordination (nnodes > 1)

    # ------------------------------------------------------------ lifecycle
    def _start_store(self) -> None:
        if self.cfg.node_rank == 0:
            from pytorch_distributed_train_tpu.native.store import StoreServer

            self.server = StoreServer(self.cfg.store_port)
            self.store_port = self.server.port
            self.coord_port = _free_port()
            # Publish the JAX coordinator endpoint for every node's workers.
            from pytorch_distributed_train_tpu.native.store import StoreClient

            with StoreClient("127.0.0.1", self.store_port) as c:
                c.set("coord", f"{self.cfg.master_addr}:{self.coord_port}"
                      .encode())
        else:
            from pytorch_distributed_train_tpu.native.store import StoreClient

            with StoreClient(self.cfg.master_addr, self.store_port,
                             timeout_ms=120_000) as c:
                coord = c.get("coord", timeout_ms=120_000).decode()
            self.coord_port = int(coord.rsplit(":", 1)[1])

    def _spawn(self, restart_gen: int) -> None:
        cfg = self.cfg
        world = cfg.nnodes * cfg.nprocs
        self.procs = []
        for local in range(cfg.nprocs):
            rank = cfg.node_rank * cfg.nprocs + local
            env = dict(os.environ)
            env.update(cfg.env or {})
            env.update({
                "PROCESS_ID": str(rank),
                "LOCAL_PROCESS_ID": str(local),
                "NUM_PROCESSES": str(world),
                "COORDINATOR_ADDRESS":
                    f"{cfg.master_addr}:{self.coord_port}",
                "TPUSTORE_ADDR": f"{cfg.master_addr}:{self.store_port}",
                "RESTART_GENERATION": str(restart_gen),
            })
            self.procs.append(subprocess.Popen(self.cmd, env=env))
        self._log(f"spawned {cfg.nprocs} workers (gen {restart_gen}, "
                  f"world {world}, coord :{self.coord_port})")

    def _kill_all(self) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 10
        for p in self.procs:
            try:
                p.wait(max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

    def _log(self, msg: str) -> None:
        print(f"[tpurun] {msg}", flush=True)

    # ---------------------------------------------------------------- run
    def run(self) -> int:
        self._start_store()
        cfg = self.cfg
        try:
            if cfg.nnodes > 1:
                from pytorch_distributed_train_tpu.native.store import (
                    StoreClient,
                )

                host = "127.0.0.1" if cfg.node_rank == 0 else cfg.master_addr
                self.agent_client = StoreClient(host, self.store_port,
                                                timeout_ms=120_000)
            for gen in range(cfg.max_restarts + 1):
                if self.agent_client is not None:
                    # Gang restarts are whole-JOB: every node's agent meets
                    # here before (re)spawning, so no generation skew.
                    self.agent_client.barrier(
                        f"agents/spawn/{gen}", cfg.nnodes, cfg.node_rank,
                        timeout_ms=600_000)
                self._spawn(gen)
                rc = self._monitor(gen)
                if rc == 0:
                    self._log("all workers exited cleanly")
                    return 0
                if gen == self.cfg.max_restarts:
                    self._log(f"worker failed (rc={rc}); restart budget "
                              f"exhausted after {gen} restarts")
                    return rc
                self._log(f"worker failed (rc={rc}); restarting gang "
                          f"({gen + 1}/{self.cfg.max_restarts})")
            return 1
        finally:
            if self.agent_client is not None:
                # Node 0 hosts the store every other agent is still polling:
                # meet before teardown, else their clients die mid-request.
                try:
                    self.agent_client.barrier(
                        "agents/exit", self.cfg.nnodes, self.cfg.node_rank,
                        timeout_ms=60_000)
                except Exception:
                    pass  # a dead peer must not wedge shutdown
                self.agent_client.close()
            if self.server is not None:
                self.server.stop()

    def _peer_failure(self, gen: int) -> int | None:
        """rc another node published for this generation, or None."""
        if self.agent_client is None:
            return None
        try:
            return int(self.agent_client.get(f"gang/fail/{gen}", timeout_ms=1))
        except TimeoutError:
            return None

    def _monitor(self, gen: int) -> int:
        """Waits for gang completion. Returns 0 (all nodes clean) or the
        first bad rc — publishing local failures to peer agents so every
        node restarts together (SPMD: the unit of restart is the job)."""
        local_done = False
        while True:
            time.sleep(self.cfg.monitor_interval_s)
            rc = self._peer_failure(gen)
            if rc is not None:
                self._kill_all()
                return rc
            if not local_done:
                codes = [p.poll() for p in self.procs]
                bad = [c for c in codes if c not in (None, 0)]
                if bad:
                    if self.agent_client is not None:
                        self.agent_client.set(f"gang/fail/{gen}",
                                              str(bad[0]).encode())
                    self._kill_all()
                    return bad[0]
                if all(c == 0 for c in codes):
                    if self.agent_client is None:
                        return 0
                    local_done = True
                    n = self.agent_client.add(f"gang/ok/{gen}", 1)
                    if n == self.cfg.nnodes:
                        self.agent_client.set(f"gang/alldone/{gen}", b"1")
            else:
                try:
                    self.agent_client.wait(f"gang/alldone/{gen}", timeout_ms=1)
                    return 0
                except TimeoutError:
                    pass  # peers still running; keep watching for failures


def worker_store():
    """Connect to the launcher's store from inside a worker (or None when
    not running under tpurun)."""
    addr = os.environ.get("TPUSTORE_ADDR")
    if not addr:
        return None
    from pytorch_distributed_train_tpu.native.store import StoreClient

    host, port = addr.rsplit(":", 1)
    return StoreClient(host, int(port))


def main(argv: list[str] | None = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="tpurun",
        description="Gang launcher with whole-job restart supervision "
                    "(the torchrun analogue).",
    )
    p.add_argument("--nprocs", type=int, required=True,
                   help="worker processes on this node")
    p.add_argument("--max-restarts", type=int, default=3)
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--node-rank", type=int, default=0)
    p.add_argument("--master-addr", default="127.0.0.1")
    p.add_argument("--store-port", type=int, default=0,
                   help="required (nonzero) when nnodes > 1")
    p.add_argument("--monitor-interval", type=float, default=0.5)
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="worker command, e.g. train.py --config ...")
    args = p.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        p.error("missing worker command")
    if args.nnodes > 1 and args.store_port == 0:
        p.error("--store-port must be fixed when nnodes > 1")
    if cmd[0].endswith(".py"):
        cmd = [sys.executable] + cmd
    cfg = LaunchConfig(
        nprocs=args.nprocs, max_restarts=args.max_restarts,
        nnodes=args.nnodes, node_rank=args.node_rank,
        master_addr=args.master_addr, store_port=args.store_port,
        monitor_interval_s=args.monitor_interval,
    )
    return ElasticAgent(cfg, cmd).run()


if __name__ == "__main__":
    sys.exit(main())
