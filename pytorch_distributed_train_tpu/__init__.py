"""TPU-native distributed training framework.

A ground-up JAX/XLA re-design of the capabilities of
``zoezhu/pytorch_distributed_train`` (a torch.distributed/NCCL harness — see
SURVEY.md; the reference mount was empty, so parity targets are pinned by
BASELINE.json and the torch 2.13.0 library sources its behavior is defined by):

- ``init_process_group('nccl')`` + DDP grad all-reduce  →  one jit-compiled
  train step over a ``jax.sharding.Mesh`` with compiler-placed collectives
  (BASELINE.json:5).
- ``DistributedSampler`` + ``DataLoader``  →  per-host sharded input pipeline
  with prefetch to HBM (data/).
- AMP/GradScaler + SGD  →  bf16 dtype policy + jitted optax update (optim.py).
- DDP/FSDP wrappers  →  GSPMD sharding annotations over mesh axes
  ``('data','fsdp','tensor','context')`` (parallel/).

Public surface mirrors the reference harness: ``Trainer``, ``TrainConfig``
presets for the five BASELINE.json config rows, and a ``train.py`` CLI.
"""

__version__ = "0.1.0"

from pytorch_distributed_train_tpu.config import (  # noqa: F401
    TrainConfig,
    get_preset,
    list_presets,
)

# Lazy top-level façade for the training/serving surface: `from
# pytorch_distributed_train_tpu import Trainer, generate` works without
# paying every submodule's import (and jit registration) cost up front.
# NOTE: no facade name may equal a submodule name ("generate" the
# function vs .generate the module): importing the submodule anywhere
# rebinds the package attribute to the MODULE, permanently shadowing the
# lazy export. The function is reachable as generate_tokens here or as
# pytorch_distributed_train_tpu.generate.generate.
_LAZY = {
    "Trainer": "pytorch_distributed_train_tpu.trainer",
    "TrainState": "pytorch_distributed_train_tpu.train_state",
    "generate_seq2seq": "pytorch_distributed_train_tpu.generate",
    "beam_search": "pytorch_distributed_train_tpu.generate",
    "beam_search_seq2seq": "pytorch_distributed_train_tpu.generate",
    "filter_logits": "pytorch_distributed_train_tpu.generate",
    "speculative_generate": "pytorch_distributed_train_tpu.speculative",
    "ContinuousBatcher": "pytorch_distributed_train_tpu.serving",
    "PagedContinuousBatcher": "pytorch_distributed_train_tpu.serving",
    "Seq2SeqContinuousBatcher": "pytorch_distributed_train_tpu.serving",
}


def __getattr__(name):
    if name == "generate_tokens":  # alias: see the note above _LAZY
        from pytorch_distributed_train_tpu.generate import generate
        return generate
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(target), name)


def __dir__():
    return sorted(list(globals()) + list(_LAZY) + ["generate_tokens"])
