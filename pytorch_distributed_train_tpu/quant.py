"""Weight-only int8 quantization for inference (decode) params.

Beyond the reference harness (its inference story is torch fp32/amp
forward); the TPU rationale: decode is HBM-bound — every generated token
re-reads all params — so storing matmul weights as int8 (+ per-output-
channel fp32 scales) halves resident param bytes vs bf16 and ~quarters
them vs fp32. Dequantization happens IN-GRAPH at the top of the jitted
decode step (quant structs are the jit inputs), so int8 is what lives in
HBM and XLA fuses the convert-multiply into the consumers where
profitable.

Scheme: symmetric per-output-channel (last axis) absmax scaling,
``w ≈ w_int8 * scale`` with ``w_int8 ∈ [-127, 127]`` — the standard
weight-only PTQ used by LLM serving stacks; per-element error is bounded
by ``scale/2 = absmax/254`` per channel.

Only ndim>=2 leaves matching ``include`` quantize (matmul kernels,
embeddings); vectors (norm scales, biases) stay fp32 — they're tiny and
quantization there hurts disproportionately.
"""

from __future__ import annotations

import re

import jax
import jax.numpy as jnp
from flax import traverse_util

# Leaf-struct keys. A dict with exactly these keys is a quantized leaf —
# still a valid pytree, so quantized trees flow through jit/device_put
# unchanged.
_W, _S = "w_int8", "scale"

DEFAULT_INCLUDE = r"(kernel|embedding)$"


def _is_quant_leaf(x) -> bool:
    return isinstance(x, dict) and set(x.keys()) == {_W, _S}


def quantize_leaf(w: jax.Array, axes: tuple[int, ...] | None = None) -> dict:
    """Symmetric int8 with absmax scales reduced over ``axes``.

    Because decode DEQUANTIZES before the matmul (no int8 arithmetic),
    any scale granularity reconstructs the weight elementwise — finer
    grouping only tightens the error bound (absmax/254 per group). Default
    grouping when ``axes`` is None:
    - 2D (in, out) kernels: reduce axis 0 → per-output-channel.
    - 3D DenseGeneral kernels: reduce axis 0 when it's the largest dim
      (the (C, heads, head_dim) q/k/v layout → per-(head, head_dim)
      scales, so one outlier head can't widen every head's step); else
      reduce the two leading axes (the (heads, head_dim, C) out-proj
      layout → per-output-channel).
    """
    if axes is None:
        if w.ndim == 3 and w.shape[0] >= max(w.shape[1:]):
            axes = (0,)
        else:
            axes = tuple(range(w.ndim - 1))
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=axes, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127)
    return {_W: q.astype(jnp.int8), _S: scale.astype(jnp.float32)}


def dequantize_leaf(q: dict, dtype=jnp.bfloat16) -> jax.Array:
    return (q[_W].astype(jnp.float32) * q[_S]).astype(dtype)


def quantize_tree(params, include: str = DEFAULT_INCLUDE):
    """Params tree → same-structure tree with matching kernels replaced by
    {w_int8, scale} structs. ``include`` is a regex over the '/'-joined
    param path (same convention as partition rules / decay_exclude)."""
    pat = re.compile(include)
    flat = traverse_util.flatten_dict(params)
    out = {}
    for path, leaf in flat.items():
        name = "/".join(map(str, path))
        if leaf.ndim >= 2 and pat.search(name):
            # Embedding tables scale per ROW (reduce the hidden axis):
            # right for lookup (each token's row has its own step) and for
            # the transposed tied-head matmul (row == output channel).
            axes = (-1,) if name.endswith("embedding") else None
            out[path] = quantize_leaf(leaf, axes)
        else:
            out[path] = leaf
    return traverse_util.unflatten_dict(out)


def dequantize_tree(params, dtype=jnp.bfloat16):
    """Inverse of quantize_tree; non-quantized leaves pass through. Call
    INSIDE the jitted consumer so the int8 arrays are what cross into the
    executable (and live in HBM)."""
    return jax.tree.map(
        lambda x: dequantize_leaf(x, dtype) if _is_quant_leaf(x) else x,
        params, is_leaf=_is_quant_leaf,
    )


def is_quantized(params) -> bool:
    return any(_is_quant_leaf(x) for x in
               jax.tree.leaves(params, is_leaf=_is_quant_leaf))


def tree_param_bytes(params) -> int:
    return sum(x.size * x.dtype.itemsize
               for x in jax.tree_util.tree_leaves(params))
