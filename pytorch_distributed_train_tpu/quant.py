"""int8 quantization: weight-only PTQ for decode + AQT-style QAT training.

Beyond the reference harness (its inference story is torch fp32/amp
forward); the TPU rationale: decode is HBM-bound — every generated token
re-reads all params — so storing matmul weights as int8 (+ per-output-
channel fp32 scales) halves resident param bytes vs bf16 and ~quarters
them vs fp32. Dequantization happens IN-GRAPH at the top of the jitted
decode step (quant structs are the jit inputs), so int8 is what lives in
HBM and XLA fuses the convert-multiply into the consumers where
profitable.

Scheme: symmetric per-output-channel (last axis) absmax scaling,
``w ≈ w_int8 * scale`` with ``w_int8 ∈ [-127, 127]`` — the standard
weight-only PTQ used by LLM serving stacks; per-element error is bounded
by ``scale/2 = absmax/254`` per channel.

Only ndim>=2 leaves matching ``include`` quantize (matmul kernels,
embeddings); vectors (norm scales, biases) stay fp32 — they're tiny and
quantization there hurts disproportionately.
"""

from __future__ import annotations

import functools
import re

import jax
import jax.numpy as jnp
from flax import traverse_util

# Leaf-struct keys. A dict with exactly these keys is a quantized leaf —
# still a valid pytree, so quantized trees flow through jit/device_put
# unchanged.
_W, _S = "w_int8", "scale"

DEFAULT_INCLUDE = r"(kernel|embedding)$"


def _is_quant_leaf(x) -> bool:
    return isinstance(x, dict) and set(x.keys()) == {_W, _S}


def quantize_leaf(w: jax.Array, axes: tuple[int, ...] | None = None) -> dict:
    """Symmetric int8 with absmax scales reduced over ``axes``.

    Because decode DEQUANTIZES before the matmul (no int8 arithmetic),
    any scale granularity reconstructs the weight elementwise — finer
    grouping only tightens the error bound (absmax/254 per group). Default
    grouping when ``axes`` is None:
    - 2D (in, out) kernels: reduce axis 0 → per-output-channel.
    - 3D DenseGeneral kernels: reduce axis 0 when it's the largest dim
      (the (C, heads, head_dim) q/k/v layout → per-(head, head_dim)
      scales, so one outlier head can't widen every head's step); else
      reduce the two leading axes (the (heads, head_dim, C) out-proj
      layout → per-output-channel).
    """
    if axes is None:
        if w.ndim == 3 and w.shape[0] >= max(w.shape[1:]):
            axes = (0,)
        else:
            axes = tuple(range(w.ndim - 1))
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=axes, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127)
    return {_W: q.astype(jnp.int8), _S: scale.astype(jnp.float32)}


def dequantize_leaf(q: dict, dtype=jnp.bfloat16) -> jax.Array:
    return (q[_W].astype(jnp.float32) * q[_S]).astype(dtype)


def quantize_tree(params, include: str = DEFAULT_INCLUDE):
    """Params tree → same-structure tree with matching kernels replaced by
    {w_int8, scale} structs. ``include`` is a regex over the '/'-joined
    param path (same convention as partition rules / decay_exclude)."""
    pat = re.compile(include)
    flat = traverse_util.flatten_dict(params)
    out = {}
    for path, leaf in flat.items():
        name = "/".join(map(str, path))
        if leaf.ndim >= 2 and pat.search(name):
            # Embedding tables scale per ROW (reduce the hidden axis):
            # right for lookup (each token's row has its own step) and for
            # the transposed tied-head matmul (row == output channel).
            axes = (-1,) if name.endswith("embedding") else None
            out[path] = quantize_leaf(leaf, axes)
        else:
            out[path] = leaf
    return traverse_util.unflatten_dict(out)


def dequantize_tree(params, dtype=jnp.bfloat16):
    """Inverse of quantize_tree; non-quantized leaves pass through. Call
    INSIDE the jitted consumer so the int8 arrays are what cross into the
    executable (and live in HBM)."""
    return jax.tree.map(
        lambda x: dequantize_leaf(x, dtype) if _is_quant_leaf(x) else x,
        params, is_leaf=_is_quant_leaf,
    )


def is_quantized(params) -> bool:
    return any(_is_quant_leaf(x) for x in
               jax.tree.leaves(params, is_leaf=_is_quant_leaf))


def tree_param_bytes(params) -> int:
    return sum(x.size * x.dtype.itemsize
               for x in jax.tree_util.tree_leaves(params))


# ===================================================== int8 TRAINING (QAT)
#
# AQT-style quantized training (beyond the reference; ROADMAP candidate):
# the big matmuls run int8×int8→int32 on the MXU — 2× the bf16 MACs/cycle
# on v5e — with dynamic symmetric absmax scales and a straight-through
# backward. Forward:
#   q(x) = clip(round(x / s_x)),  s_x = absmax over the CONTRACTION dims
#          (per-token rows for activations, per-output-channel for weights)
#   out  = dot_int32(q(x), q(w)) · s_x ⊗ s_w        (exact rescale)
# Backward: gradients of the UNQUANTIZED dot at the original values (STE —
# quantization treated as identity). Scales carry stop_gradient, matching
# AQT's default. Injected into flax layers via their `dot_general` arg, so
# model code doesn't change shape: see models/llama.py quant_training.


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _int8_dot(lhs, rhs, dimension_numbers):
    (lc, rc), (lb, rb) = dimension_numbers
    assert not lb and not rb, "int8 dot: batch dims unsupported"
    ql, sl = _dyn_quant(lhs, lc)
    qr, sr = _dyn_quant(rhs, rc)
    out32 = jax.lax.dot_general(ql, qr, dimension_numbers,
                                preferred_element_type=jnp.int32)
    sl_f = jnp.squeeze(sl, lc)  # (lhs free dims...)
    sr_f = jnp.squeeze(sr, rc)  # (rhs free dims...)
    out = out32.astype(jnp.float32)
    out = out * sl_f.reshape(sl_f.shape + (1,) * sr_f.ndim) * sr_f
    return out.astype(lhs.dtype)


def _dyn_quant(x, contract_axes):
    a = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=contract_axes,
                keepdims=True)
    s = jax.lax.stop_gradient(jnp.where(a > 0, a / 127.0, 1.0))
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / s), -127, 127)
    return q.astype(jnp.int8), s


def _int8_dot_fwd(lhs, rhs, dimension_numbers):
    return _int8_dot(lhs, rhs, dimension_numbers), (lhs, rhs)


def _int8_dot_bwd(dimension_numbers, res, g):
    lhs, rhs = res

    def fp_dot(a, b):
        return jax.lax.dot_general(a, b, dimension_numbers,
                                   preferred_element_type=g.dtype)

    _, vjp = jax.vjp(fp_dot, lhs, rhs)
    return vjp(g)


_int8_dot.defvjp(_int8_dot_fwd, _int8_dot_bwd)


def quant_dot_general(quant: str):
    """Map a quant_training knob value onto a flax ``dot_general``
    override (None = the default fp path). The one switch models share
    (llama / llama_pp / gpt2 thread it into Dense/DenseGeneral)."""
    if not quant:
        return None
    if quant == "int8":
        return int8_dot_general
    raise ValueError(f"quant_training must be ''|'int8', got {quant!r}")


def int8_dot_general(lhs, rhs, dimension_numbers, precision=None,
                     preferred_element_type=None):
    """Drop-in ``dot_general`` for flax Dense/DenseGeneral (their call
    signature) running the AQT int8 forward + STE backward above.
    ``precision``/``preferred_element_type`` are accepted for signature
    compatibility; the int8 path fixes its own accumulation type."""
    del precision, preferred_element_type
    return _int8_dot(lhs, rhs, dimension_numbers)
