"""int8 quantization: weight-only PTQ for decode + AQT-style QAT training.

Beyond the reference harness (its inference story is torch fp32/amp
forward); the TPU rationale: decode is HBM-bound — every generated token
re-reads all params — so storing matmul weights as int8 (+ per-output-
channel fp32 scales) halves resident param bytes vs bf16 and ~quarters
them vs fp32. Dequantization happens IN-GRAPH at the top of the jitted
decode step (quant structs are the jit inputs), so int8 is what lives in
HBM and XLA fuses the convert-multiply into the consumers where
profitable.

Scheme: symmetric per-output-channel (last axis) absmax scaling,
``w ≈ w_int8 * scale`` with ``w_int8 ∈ [-127, 127]`` — the standard
weight-only PTQ used by LLM serving stacks; per-element error is bounded
by ``scale/2 = absmax/254`` per channel.

Only ndim>=2 leaves matching ``include`` quantize (matmul kernels,
embeddings); vectors (norm scales, biases) stay fp32 — they're tiny and
quantization there hurts disproportionately.
"""

from __future__ import annotations

import functools
import re

import jax
import jax.numpy as jnp
import numpy as np
from flax import traverse_util

# Leaf-struct keys. A dict with exactly these keys is a quantized leaf —
# still a valid pytree, so quantized trees flow through jit/device_put
# unchanged.
_W, _S = "w_int8", "scale"
_W4 = "w_int4"

DEFAULT_INCLUDE = r"(kernel|embedding)$"


def _is_quant_leaf(x) -> bool:
    return isinstance(x, dict) and set(x.keys()) in ({_W, _S}, {_W4, _S})


def quantize_leaf(w: jax.Array, axes: tuple[int, ...] | None = None) -> dict:
    """Symmetric int8 with absmax scales reduced over ``axes``.

    Because decode DEQUANTIZES before the matmul (no int8 arithmetic),
    any scale granularity reconstructs the weight elementwise — finer
    grouping only tightens the error bound (absmax/254 per group). Default
    grouping when ``axes`` is None:
    - 2D (in, out) kernels: reduce axis 0 → per-output-channel.
    - 3D DenseGeneral kernels: reduce axis 0 when it's the largest dim
      (the (C, heads, head_dim) q/k/v layout → per-(head, head_dim)
      scales, so one outlier head can't widen every head's step); else
      reduce the two leading axes (the (heads, head_dim, C) out-proj
      layout → per-output-channel).
    """
    if axes is None:
        if w.ndim == 3 and w.shape[0] >= max(w.shape[1:]):
            axes = (0,)
        else:
            axes = tuple(range(w.ndim - 1))
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=axes, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127)
    return {_W: q.astype(jnp.int8), _S: scale.astype(jnp.float32)}


def quantize_leaf_int4(w: jax.Array, group_size: int = 128) -> dict:
    """Symmetric int4 (±7) with GROUP-wise absmax scales along the
    largest axis.

    Half the RESIDENT HBM of int8 again. Bandwidth caveat (round-5 AOT
    finding, AOT_AB.json): on the dequantize-before-matmul path XLA
    materializes the bf16 weights each step, so per-step HBM TRAFFIC
    is bf16-sized regardless of storage width (int4's extra unpack
    even adds temps) — the capacity win is real, the latency win needs
    the fused in-VMEM dequant kernels (ops/quant_matmul.py). int4's 15 levels need finer scale
    granularity than a whole channel: groups of ``group_size`` along the
    array's largest axis (any grouping reconstructs the weight
    elementwise since decode dequantizes BEFORE the matmul — see
    quantize_leaf; finer groups only tighten the absmax/14 error bound).
    When the axis doesn't divide, the whole axis is one group (int8-style
    granularity at int4 width). Scale shape = w.shape with the grouped
    axis split to (n_groups, 1) — w.ndim+1 dims, so the dequant can
    recover the grouping from shapes alone (no metadata in the struct).
    Storage: jnp.int4 (XLA packs two per byte on TPU; numpy-side arrays
    are byte-per-element, so host-RAM savings appear only on device).
    """
    axis = int(np.argmax(w.shape))
    K = w.shape[axis]
    G = group_size if group_size > 0 and K % group_size == 0 else K
    grouped = w.shape[:axis] + (K // G, G) + w.shape[axis + 1:]
    w32 = w.astype(jnp.float32).reshape(grouped)
    absmax = jnp.max(jnp.abs(w32), axis=axis + 1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 7.0, 1.0)
    q = jnp.clip(jnp.round(w32 / scale), -7, 7)
    return {_W4: q.reshape(w.shape).astype(jnp.int4),
            _S: scale.astype(jnp.float32)}


def _int4_grouping(w_shape, scale_shape):
    """Recover (axis, group) from the shape relation quantize_leaf_int4
    establishes: scale has one extra dim, inserted at the grouped axis."""
    for i in range(len(w_shape)):
        ng = scale_shape[i]
        if (scale_shape[:i] == w_shape[:i]
                and scale_shape[i + 1] == 1
                and scale_shape[i + 2:] == w_shape[i + 1:]
                and ng > 0 and w_shape[i] % ng == 0):
            return i, w_shape[i] // ng
    raise ValueError(f"unrecognized int4 scale shape {scale_shape} "
                     f"for weight {w_shape}")


def dequantize_leaf(q: dict, dtype=jnp.bfloat16) -> jax.Array:
    if _W4 in q:
        w, scale = q[_W4], q[_S]
        axis, G = _int4_grouping(w.shape, scale.shape)
        grouped = w.shape[:axis] + (w.shape[axis] // G, G) + w.shape[axis + 1:]
        out = w.astype(jnp.float32).reshape(grouped) * scale
        return out.reshape(w.shape).astype(dtype)
    return (q[_W].astype(jnp.float32) * q[_S]).astype(dtype)


def quantize_tree(params, include: str = DEFAULT_INCLUDE, bits: int = 8,
                  group_size: int = 128):
    """Params tree → same-structure tree with matching kernels replaced by
    {w_int8|w_int4, scale} structs. ``include`` is a regex over the
    '/'-joined param path (same convention as partition rules /
    decay_exclude); ``bits`` selects the width (8 = per-channel scales,
    4 = group-wise, see quantize_leaf_int4)."""
    if bits not in (4, 8):
        raise ValueError(f"quantize bits must be 4 or 8, got {bits}")
    pat = re.compile(include)
    flat = traverse_util.flatten_dict(params)
    out = {}
    for path, leaf in flat.items():
        name = "/".join(map(str, path))
        if leaf.ndim >= 2 and pat.search(name):
            if bits == 4:
                out[path] = quantize_leaf_int4(leaf, group_size)
            else:
                # Embedding tables scale per ROW (reduce the hidden axis):
                # right for lookup (each token's row has its own step) and
                # for the transposed tied-head matmul (row == out channel).
                axes = (-1,) if name.endswith("embedding") else None
                out[path] = quantize_leaf(leaf, axes)
        else:
            out[path] = leaf
    return traverse_util.unflatten_dict(out)


def dequantize_tree(params, dtype=jnp.bfloat16):
    """Inverse of quantize_tree; non-quantized leaves pass through. Call
    INSIDE the jitted consumer so the int8 arrays are what cross into the
    executable (and live in HBM)."""
    return jax.tree.map(
        lambda x: dequantize_leaf(x, dtype) if _is_quant_leaf(x) else x,
        params, is_leaf=_is_quant_leaf,
    )


def is_quantized(params) -> bool:
    return any(_is_quant_leaf(x) for x in
               jax.tree.leaves(params, is_leaf=_is_quant_leaf))


def tree_param_bytes(params) -> int:
    """Logical parameter bytes (int4 counts 0.5/elem — what the packed
    DEVICE representation costs; numpy-side int4 arrays are stored a byte
    per element, so host RAM differs)."""
    total = 0.0
    for x in jax.tree_util.tree_leaves(params):
        if x.dtype == jnp.int4:
            total += x.size * 0.5
        else:
            total += x.size * x.dtype.itemsize
    return int(total)


# ===================================================== int8 TRAINING (QAT)
#
# AQT-style quantized training (beyond the reference; ROADMAP candidate):
# the big matmuls run int8×int8→int32 on the MXU — 2× the bf16 MACs/cycle
# on v5e — with dynamic symmetric absmax scales and a straight-through
# backward. Forward:
#   q(x) = clip(round(x / s_x)),  s_x = absmax over the CONTRACTION dims
#          (per-token rows for activations, per-output-channel for weights)
#   out  = dot_int32(q(x), q(w)) · s_x ⊗ s_w        (exact rescale)
# Backward: gradients of the UNQUANTIZED dot at the original values (STE —
# quantization treated as identity). Scales carry stop_gradient, matching
# AQT's default. Injected into flax layers via their `dot_general` arg, so
# model code doesn't change shape: see models/llama.py quant_training.


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _int8_dot(lhs, rhs, dimension_numbers):
    (lc, rc), (lb, rb) = dimension_numbers
    assert not lb and not rb, "int8 dot: batch dims unsupported"
    ql, sl = _dyn_quant(lhs, lc)
    qr, sr = _dyn_quant(rhs, rc)
    out32 = jax.lax.dot_general(ql, qr, dimension_numbers,
                                preferred_element_type=jnp.int32)
    sl_f = jnp.squeeze(sl, lc)  # (lhs free dims...)
    sr_f = jnp.squeeze(sr, rc)  # (rhs free dims...)
    out = out32.astype(jnp.float32)
    out = out * sl_f.reshape(sl_f.shape + (1,) * sr_f.ndim) * sr_f
    return out.astype(lhs.dtype)


def _dyn_quant(x, contract_axes):
    a = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=contract_axes,
                keepdims=True)
    s = jax.lax.stop_gradient(jnp.where(a > 0, a / 127.0, 1.0))
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / s), -127, 127)
    return q.astype(jnp.int8), s


def _int8_dot_fwd(lhs, rhs, dimension_numbers):
    return _int8_dot(lhs, rhs, dimension_numbers), (lhs, rhs)


def _int8_dot_bwd(dimension_numbers, res, g):
    lhs, rhs = res

    def fp_dot(a, b):
        return jax.lax.dot_general(a, b, dimension_numbers,
                                   preferred_element_type=g.dtype)

    _, vjp = jax.vjp(fp_dot, lhs, rhs)
    return vjp(g)


_int8_dot.defvjp(_int8_dot_fwd, _int8_dot_bwd)


def quant_dot_general(quant: str):
    """Map a quant_training knob value onto a flax ``dot_general``
    override (None = the default fp path). The one switch models share
    (llama / llama_pp / gpt2 thread it into Dense/DenseGeneral)."""
    if not quant:
        return None
    if quant == "int8":
        return int8_dot_general
    raise ValueError(f"quant_training must be ''|'int8', got {quant!r}")


def int8_dot_general(lhs, rhs, dimension_numbers, precision=None,
                     preferred_element_type=None):
    """Drop-in ``dot_general`` for flax Dense/DenseGeneral (their call
    signature) running the AQT int8 forward + STE backward above.
    ``precision``/``preferred_element_type`` are accepted for signature
    compatibility; the int8 path fixes its own accumulation type."""
    del precision, preferred_element_type
    return _int8_dot(lhs, rhs, dimension_numbers)


def weight_key(leaf: dict) -> str:
    """The weight key of a quant struct ('w_int8' or 'w_int4')."""
    return _W if _W in leaf else _W4


def quantize_tree_named(params, mode: str):
    """CLI-string dispatch ('int8'|'int4') — THE mapping every entrypoint
    (bench decode/serve arms, serving.load_params_for_serving) goes
    through, so a bench can never measure a different quantization recipe
    than the server loads."""
    if mode not in ("int8", "int4"):
        raise ValueError(f"quantize must be 'int8' or 'int4', got {mode!r}")
    return quantize_tree(params, bits=8 if mode == "int8" else 4)
