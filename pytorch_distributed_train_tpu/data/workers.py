"""Multi-process shared-memory decode plane (ROADMAP item 2, ISSUE 12a).

The GIL wall this replaces: one chip consumes 2541 ResNet images/s
(BENCH_LKG) while the host pipeline delivers 340-985 img/s — the decode
and augment work runs in ONE Python process, and threads only help
where PIL/numpy drop the GIL. This pool runs the decode in N forked
worker PROCESSES (the torch DataLoader worker model, SURVEY C17,
torch:utils/data/_utils/worker.py:244) with one crucial difference:
decoded pixel batches come back through preallocated SHARED-MEMORY ring
slots, not a pickle stream — the parent pays one memcpy per batch, the
workers never serialize pixels.

Design points:

- **fork, not spawn**: workers are created with the POSIX fork context,
  so the ``make_batch`` closure (dataset handle included) is inherited
  by address space, never pickled. Task messages carry only index
  arrays and small ints. Platforms without fork degrade to in-process
  loading (``available()`` gates the pool at the loaders).
- **anonymous shared mappings**: ring slots are ``mmap.mmap(-1, n)``
  MAP_SHARED|MAP_ANONYMOUS regions created BEFORE the fork — no
  /dev/shm names, no resource-tracker bookkeeping, freed with the
  processes. Each slot holds one host batch; a worker writes the raw
  array bytes and ships a tiny (key, dtype, shape, offset) layout over
  the result queue.
- **ordered delivery, composition-exact**: tasks are numbered; a
  reorder buffer yields batch b strictly in submission order, so the
  byte-level batch stream is IDENTICAL to the in-process path (the
  PR 6 invariant: batch composition and ``start_batch`` resume must be
  invariant to how the work is parallelized). Randomness never depends
  on worker scheduling because every task carries its own rng key
  material — the loaders' (seed, epoch, batch/record) keying runs
  inside the worker unchanged.
- **per-worker stage timers**: workers accumulate the same
  read/decode/augment stage seconds (obs/perf.py) their dataset code
  already emits — process-locally — and ship the per-batch delta with
  each result; the parent merges the deltas into the process-global
  ``input_stage_seconds_total`` attribution, so the staged stall split
  keeps working when the stages run in other processes.
- **epoch tokens**: an abandoned epoch (early break, step cap) leaves
  in-flight tasks behind; results are stamped with the submitting
  epoch's token and stale arrivals are dropped (slot reclaimed), so the
  next epoch can never interleave another epoch's batches.

The pool is deliberately loader-agnostic: ``make_batch(task) -> dict``
is supplied by the threads loader (data/pipeline.py) and the grain
loader (data/grain_pipeline.py), each preserving its own rng-keying
convention.
"""

from __future__ import annotations

import mmap
import os
import queue
import threading
import time
import traceback
from typing import Callable, Iterable, Iterator

import numpy as np

from pytorch_distributed_train_tpu.obs import perf as perf_lib
from pytorch_distributed_train_tpu.obs.registry import get_registry


def available() -> bool:
    """The pool needs POSIX fork (closure inheritance — see module doc)."""
    return hasattr(os, "fork")


def process_thread_budget(solo_threads: int) -> int:
    """Per-process thread fan-out for decode helpers (item/record thread
    pools): the solo count, clamped by the PDTT_NATIVE_THREADS budget a
    pool worker runs under (x2 — decode threads block on I/O about half
    the time, C++ threads don't). The one definition both loaders' module
    pools share."""
    env = os.environ.get("PDTT_NATIVE_THREADS")
    if env:
        try:
            return max(1, min(solo_threads, max(1, int(env)) * 2))
        except ValueError:
            pass
    return max(1, solo_threads)


def python_thread_budget(solo_threads: int) -> int:
    """Per-process thread budget for PYTHON/PIL decode pools — the x2
    I/O allowance of ``process_thread_budget`` removed.

    The x2 was sized for C++ decode (libjpeg/imgops release the GIL for
    the whole call); PIL item decode holds the GIL through its Python
    framing, so N pool workers each running 2x their core share contend
    instead of overlapping — the LKG ``pil_grain_mp8`` regression (424
    img/s vs plain threads' 444, ISSUE 14 satellite): 8 forked workers
    x (2 cores x2 = 4) PIL threads = 32 GIL-bound threads on a 24-core
    host. Inside a pool worker this clamps to exactly the worker's
    PDTT_NATIVE_THREADS core share."""
    env = os.environ.get("PDTT_NATIVE_THREADS")
    if env:
        try:
            return max(1, min(solo_threads, max(1, int(env))))
        except ValueError:
            pass
    return max(1, solo_threads)


def worker_core_share(num_workers: int, avail: int | None = None) -> int:
    """Per-worker core share of the pool: (cpus - 1) split across the
    workers, floor 1 — THE definition, used both at fork time (the
    PDTT_NATIVE_THREADS budget each worker runs under) and by the
    parent-side mirrors that report/warn about it
    (``pool_decode_threads``, the grain clamp note). One formula so the
    gauge/ledger identity can never drift from what the workers
    actually use."""
    if avail is None:
        avail = os.cpu_count() or 2
    return max(1, (avail - 1) // max(num_workers, 1))


def pool_decode_threads(num_workers: int, solo_threads: int = 8,
                        avail: int | None = None) -> int:
    """The PIL-decode thread count ONE forked pool worker will use —
    the parent-side mirror of ``python_thread_budget`` under the
    per-worker core share the pool sets at fork (worker_core_share).
    Lets loaders/benches report and warn about the total decode fan-out
    before any worker forks."""
    if avail is None:
        avail = os.cpu_count() or 2
    if num_workers <= 0:
        return max(1, min(solo_threads, avail))
    return max(1, min(solo_threads, worker_core_share(num_workers, avail)))


def pool_budget(requested: int, avail: int | None = None) -> int:
    """Worker-process budget for the shared-memory pool.

    One core always stays with the consumer/train loop (same rationale
    as grain_pipeline.bounded_workers), but unlike grain's clamp the
    floor is 0 only when the caller asked for 0: a 1-core host with
    ``mp_workers>0`` gets 1 worker, because the pool's workers block on
    a queue when idle instead of spinning grain's IPC machinery — the
    measured pathology behind the old clamp-to-zero does not apply.
    """
    if requested <= 0:
        return 0
    if avail is None:
        avail = os.cpu_count() or 1
    return max(1, min(requested, avail - 1))


def _write_slot(view: memoryview, batch: dict) -> list | None:
    """Serialize a batch dict's raw bytes into one ring slot.

    Returns the (key, dtype-str, shape, offset) layout, or None when the
    batch doesn't fit (caller falls back to the pickle path — loud, and
    counted)."""
    off = 0
    layout = []
    for k in sorted(batch):
        a = np.ascontiguousarray(batch[k])
        n = a.nbytes
        if off + n > len(view):
            return None
        view[off:off + n] = memoryview(a).cast("B")
        layout.append((k, a.dtype.str, a.shape, off))
        off += n
    return layout


def _read_slot(view: memoryview, layout: list) -> dict:
    """Copy a batch back out of a ring slot (the one memcpy the parent
    pays; the slot is reusable the moment this returns)."""
    out = {}
    for k, dtype, shape, off in layout:
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        out[k] = np.frombuffer(
            view, dtype=np.dtype(dtype), count=n, offset=off
        ).reshape(shape).copy()
    return out


def reset_thread_local_state(dataset) -> None:
    """Drop a dataset's per-thread handle caches after a fork.

    fork duplicates the fd table but file OFFSETS live in the shared
    open-file description: a TarShardImageDataset handle opened in the
    parent before the fork would have every worker (and the parent)
    seek/read through the SAME offset — racing reads return other
    workers' bytes. The pickle path already drops `_local`
    (__getstate__); this is the fork-path equivalent, called by
    _worker_main before any task runs."""
    if hasattr(dataset, "_local"):
        import threading as _threading

        dataset._local = _threading.local()


def _worker_main(task_q, result_q, views, make_batch,
                 native_threads: int = 0, post_fork=None) -> None:
    """Worker loop: drain tasks, decode, write the slot, ship the layout
    plus the batch's stage-seconds delta. Runs until the None sentinel.

    Never touches jax (the obs/ package contract keeps perf_lib
    jax-free); errors ship as formatted tracebacks — the parent raises
    them on the consumer thread."""
    # Shed the parent's inherited diagnostics: the trainer installs
    # signal-dump handlers (flight recorder, faulthandler SIGTERM
    # stacks) that a torn-down decode worker must not replay — a worker
    # dying at parent exit is routine, not an incident.
    import faulthandler
    import signal

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, signal.SIG_DFL)
        except (OSError, ValueError):
            pass
    try:
        faulthandler.disable()
    except Exception:
        pass
    if native_threads > 0:
        # Split the host's C++ decode/augment thread budget across the
        # pool: N workers each running the SOLO default (up to 8
        # libjpeg/imgops threads) oversubscribe the host into a
        # slowdown — measured 607 vs 2235 img/s on the 24-core bench
        # box before this cap.
        os.environ["PDTT_NATIVE_THREADS"] = str(native_threads)
    if post_fork is not None:
        post_fork()
    stats = perf_lib.get_input_stats()
    reg = get_registry()
    # Counters this worker's dataset/fault code bumps (cache reads,
    # decode retries/substitutions) live in the CHILD's registry copy;
    # each result ships the per-batch counter delta home so the
    # parent's /metrics stays whole-pipeline. input_stage_seconds_total
    # is excluded: the stage-seconds merge below already re-increments
    # it parent-side.
    _SKIP = ("input_stage_seconds_total",)

    def _counters():
        return {k: v for k, v in reg.counter_values().items()
                if k[0] not in _SKIP}

    while True:
        msg = task_q.get()
        if msg is None:
            return
        token, seq, slot, task = msg
        before = dict(stats.seconds)
        c_before = _counters()
        t0 = time.monotonic()
        try:
            batch = make_batch(task)
            layout = _write_slot(views[slot], batch)
            busy = time.monotonic() - t0
            delta = {s: stats.seconds[s] - before.get(s, 0.0)
                     for s in stats.seconds
                     if stats.seconds[s] > before.get(s, 0.0)}
            c_delta = {k: v - c_before.get(k, 0.0)
                       for k, v in _counters().items()
                       if v > c_before.get(k, 0.0)}
            if layout is None:
                # Oversized batch (shouldn't happen with static shapes;
                # ragged text tails can): pickle path keeps correctness.
                result_q.put((token, seq, "pickle", slot, batch, delta,
                              c_delta, busy))
            else:
                result_q.put((token, seq, "shm", slot, layout, delta,
                              c_delta, busy))
        except BaseException:
            result_q.put((token, seq, "error", slot,
                          traceback.format_exc(), {}, {},
                          time.monotonic() - t0))


class SharedMemoryWorkerPool:
    """N forked decode processes + a shared-memory result ring.

    ``run(tasks)`` is a generator: it computes the FIRST task in the
    parent (sizing the ring from its byte footprint on first use, and
    warming dataset caches the way the in-process path would), then
    streams the remaining tasks through the workers, yielding batches
    in task order. One pool instance serves many epochs; ``close()``
    (also registered via the workers being daemonic) tears it down.
    """

    def __init__(self, make_batch: Callable[[object], dict],
                 num_workers: int, *, slots: int = 0,
                 slot_headroom: float = 1.1, post_fork=None):
        if num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, got {num_workers}")
        if not available():
            raise RuntimeError(
                "SharedMemoryWorkerPool needs os.fork (POSIX)")
        self.make_batch = make_batch
        self.post_fork = post_fork
        self.num_workers = num_workers
        self.slots = slots or num_workers + 2
        self._headroom = slot_headroom
        self._started = False
        self._closed = False
        self._token = 0
        self._procs: list = []
        self._maps: list[mmap.mmap] = []
        self._views: list[memoryview] = []
        self._task_q = None
        self._result_q = None
        # Parent-side slot free-list: plain queue.Queue — only parent
        # threads (submitter + consumer generator) touch it.
        self._free: queue.Queue = queue.Queue()
        self._abort = threading.Event()
        reg = get_registry()
        self._g_workers = reg.gauge(
            "input_worker_pool_workers",
            help="shared-memory decode pool size (worker processes); 0 "
                 "when the pool is off")
        self._g_occupancy = reg.gauge(
            "input_worker_occupancy",
            help="decode-pool busy fraction (busy worker-seconds over "
                 "pool capacity since the epoch started)")
        self._c_batches = reg.counter(
            "input_worker_batches_total",
            help="batches decoded by shared-memory pool workers")
        self._c_busy = reg.counter(
            "input_worker_busy_seconds_total",
            help="cumulative busy seconds across decode-pool workers")
        self._c_fallback = reg.counter(
            "input_worker_fallback_total",
            help="pool batches that overflowed their ring slot and "
                 "shipped pickled (oversized batch — ring undersized)")

    # ------------------------------------------------------------ lifecycle
    def _start(self, slot_bytes: int) -> None:
        import multiprocessing as mp

        ctx = mp.get_context("fork")
        self._task_q = ctx.Queue()
        self._result_q = ctx.Queue()
        for i in range(self.slots):
            m = mmap.mmap(-1, slot_bytes)  # anonymous MAP_SHARED region
            self._maps.append(m)
            self._views.append(memoryview(m))
            self._free.put(i)
        import warnings

        with warnings.catch_warnings():
            # jax warns on ANY os.fork under its threads; these workers
            # never touch jax (decode is numpy/PIL/native), so the
            # deadlock it warns about cannot involve a jax lock. The
            # start is done from the consumer side before batches flow.
            warnings.filterwarnings(
                "ignore", message=".*os.fork.*", category=RuntimeWarning)
            native_threads = worker_core_share(self.num_workers)
            for _ in range(self.num_workers):
                p = ctx.Process(
                    target=_worker_main,
                    args=(self._task_q, self._result_q, self._views,
                          self.make_batch, native_threads,
                          self.post_fork),
                    daemon=True)
                p.start()
                self._procs.append(p)
        self._started = True
        self._g_workers.set(self.num_workers)

    def close(self) -> None:
        """Stop workers and release the ring. Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._abort.set()
        if self._started:
            for _ in self._procs:
                try:
                    self._task_q.put(None)
                except (OSError, ValueError):
                    pass
            for p in self._procs:
                p.join(timeout=5.0)
                if p.is_alive():
                    p.terminate()
            # release queue feeder threads before unmapping
            for q_ in (self._task_q, self._result_q):
                try:
                    q_.close()
                    q_.join_thread()
                except (OSError, ValueError):
                    pass
            for v in self._views:
                v.release()
            for m in self._maps:
                try:
                    m.close()
                except BufferError:
                    pass  # a copied-out view still alive somewhere
        self._g_workers.set(0)

    def __del__(self):  # best-effort; daemons die with the parent anyway
        try:
            self.close()
        except Exception:
            pass

    # -------------------------------------------------------------- running
    def _slot_bytes_for(self, batch: dict) -> int:
        total = sum(np.ascontiguousarray(v).nbytes for v in batch.values())
        return max(1 << 16, int(total * self._headroom) + 4096)

    def run(self, tasks: Iterable) -> Iterator[dict]:
        """Stream ``tasks`` through the pool, yielding batches in order.

        One epoch owns the pool at a time, but an ABANDONED epoch's
        generator may still be suspended (a producer thread that hasn't
        been collected yet) when the next one starts: every epoch gets
        its OWN abort event (a stale generator's teardown can then never
        kill its successor), and a consumer that sees a NEWER token —
        in a message, or on the pool itself — hands the message back
        and retires, so two overlapping generators can't steal each
        other's batches."""
        if self._closed:
            raise RuntimeError("worker pool is closed")
        it = iter(tasks)
        first = next(it, None)
        if first is None:
            return
        # First batch in-parent: sizes the ring on first use and keeps
        # the epoch's first yield latency equal to the in-process path
        # (workers fill the ring behind it).
        batch0 = self.make_batch(first)
        if not self._started:
            self._start(self._slot_bytes_for(batch0))
        self._token += 1
        token = self._token
        abort = threading.Event()  # THIS epoch's, never a successor's
        self._abort = abort        # close() aborts the current epoch
        yield batch0

        submitted = [0]
        done = threading.Event()

        def _submit():
            n = 0
            try:
                for task in it:
                    slot = None
                    while slot is None:
                        if abort.is_set():
                            return
                        try:
                            slot = self._free.get(timeout=0.1)
                        except queue.Empty:
                            continue
                    self._task_q.put((token, n, slot, task))
                    n += 1
            finally:
                submitted[0] = n
                done.set()

        submitter = threading.Thread(target=_submit, daemon=True)
        submitter.start()
        t_epoch0 = time.monotonic()
        busy_total = 0.0
        pending: dict[int, dict] = {}
        next_seq = 0
        stats = perf_lib.get_input_stats()
        try:
            while True:
                if done.is_set() and next_seq >= submitted[0] \
                        and not pending:
                    return
                if self._token != token:
                    return  # a newer epoch owns the pool; retire quietly
                try:
                    msg = self._result_q.get(timeout=0.1)
                except queue.Empty:
                    dead = [p for p in self._procs if not p.is_alive()]
                    if dead:
                        # A worker died mid-epoch (OOM kill, segfault):
                        # its in-flight seq would block the reorder
                        # buffer forever — fail LOUDLY instead.
                        raise RuntimeError(
                            f"{len(dead)}/{len(self._procs)} shared-"
                            "memory decode worker(s) died (exitcodes "
                            f"{[p.exitcode for p in dead]}) — batch "
                            f"{next_seq} can never arrive")
                    continue
                tok, seq, kind, slot, payload, stage_delta, c_delta, \
                    busy = msg
                if tok != token:
                    if tok > token:
                        # a successor epoch's result — hand it back and
                        # retire; dropping it would wedge that epoch
                        self._result_q.put(msg)
                        return
                    self._free.put(slot)  # stale epoch: reclaim only
                    continue
                if kind == "error":
                    self._free.put(slot)
                    raise RuntimeError(
                        f"decode worker failed on batch {seq}:\n{payload}")
                if kind == "pickle":
                    self._c_fallback.inc()
                    batch = payload
                    self._free.put(slot)
                else:
                    batch = _read_slot(self._views[slot], payload)
                    self._free.put(slot)
                stats.merge(stage_delta)
                if c_delta:
                    get_registry().merge_counter_deltas(c_delta)
                busy_total += busy
                self._c_batches.inc()
                self._c_busy.inc(busy)
                elapsed = time.monotonic() - t_epoch0
                if elapsed > 0:
                    self._g_occupancy.set(
                        min(1.0, busy_total / (self.num_workers * elapsed)))
                pending[seq] = batch
                while next_seq in pending:
                    yield pending.pop(next_seq)
                    next_seq += 1
        finally:
            abort.set()
            submitter.join(timeout=5.0)
