"""Grain-backed host loader — the multiprocess alternative to the threaded
HostDataLoader (SURVEY C17: torch's DataLoader runs worker *processes*,
torch:utils/data/_utils/worker.py:244; Grain is the JAX-ecosystem loader
with the same process-pool design).

Selected via ``DataConfig.loader = "grain"``. Duck-types HostDataLoader
(``steps_per_epoch``, ``epoch(epoch, start_batch)``) so the rest of the
input pipeline — producer thread, HBM prefetch, sync checks — is shared.

Reuses the datasets unchanged. The element grain moves is a whole HOST
BATCH of record indices (round-5 restructure — BASELINE.md "grain
gap"): batching lives in the SOURCE, before grain's worker sharding,
so batch composition is invariant to worker_count and a mid-epoch
resume slices the epoch order at exact batch boundaries (see
_BatchIndexSource for why operation-level gp.Batch cannot give
either). One map call per batch also amortizes grain's per-element
machinery by the batch size and hands the native batch decoder
(native/jpegdec.cpp) real batches. Augment randomness does NOT use
Grain's sampler-position rng: item-style records key their rng on
(seed, epoch, record index) — bit-exact under ANY regrouping — and
``get_batch`` loads on (seed, epoch, the batch's full index tuple),
which the source's batch-boundary invariant makes resume-exact.

Sharding/shuffle semantics mirror DistributedSampler (C16): per-epoch
reseeded shuffle, host-sharded with drop_remainder — though the shuffle
permutation itself is Grain's, not byte-identical to data/sampler.py.
"""

from __future__ import annotations

import os
from typing import Iterator

import jax
import numpy as np

from pytorch_distributed_train_tpu.obs.spans import span as _span

# Process-local decode pool for per-record get_item calls inside the
# batched map (see _make_load_transform). A module global, NOT transform
# state: MapTransform instances pickle into grain worker processes and a
# ThreadPoolExecutor does not — each worker process (or the in-process
# worker_count=0 path) lazily builds its own. Pid-guarded: the shared-
# memory decode pool (data/workers.py) FORKS its workers, and executor
# threads never survive a fork.
_DECODE_POOL = None


def _decode_pool():
    global _DECODE_POOL
    if _DECODE_POOL is None or _DECODE_POOL[0] != os.getpid():
        from concurrent.futures import ThreadPoolExecutor

        from pytorch_distributed_train_tpu.data import workers as workers_lib

        # python_thread_budget, NOT process_thread_budget: this pool
        # runs PIL item decode (GIL-holding Python framing), and the
        # native budget's x2 I/O allowance composed pathologically with
        # data.mp_workers — N forked workers x 2x-their-core-share PIL
        # threads oversubscribed the host into the LKG pil_grain_mp8
        # regression (424 vs 444 img/s; ISSUE 14 satellite).
        _DECODE_POOL = (os.getpid(), ThreadPoolExecutor(
            max_workers=workers_lib.python_thread_budget(
                min(8, os.cpu_count() or 1)),
            thread_name_prefix="grain-decode"))
    return _DECODE_POOL[1]


# Log each distinct clamp once per process — a per-epoch warning for the
# same configured count is noise, silence is an unexplained throughput
# drop (satellite: grain clamp fix, ISSUE 12).
_CLAMP_LOGGED: set = set()


def _effective_workers_gauge(loader: str):
    from pytorch_distributed_train_tpu.obs.registry import get_registry

    return get_registry().gauge(
        "input_effective_workers", labels={"loader": loader},
        help="effective input-pipeline worker count after host/pool "
             "clamping (processes; 0 = in-process loading)")


def bounded_workers(requested: int, avail: int | None = None, *,
                    pool_budget: int = 0) -> int:
    """Cap Grain worker PROCESSES by what the host can actually run.

    Worker processes exist to escape the GIL onto OTHER cores
    (torch:utils/data/_utils/worker.py:244 — same rationale); on a host
    with no core to spare they only add spawn+IPC contention against the
    consumer. Measured on this repo's 1-core sandbox: the uncapped
    process arm produced no batch within 550 s (BASELINE.md r2 "DNF"),
    while worker_count=0 (in-process loading, Grain's supported
    degenerate mode) streams fine. Cap = cpu_count - 1 (one core stays
    with the consumer/train loop), never more than requested.

    With the shared-memory pool enabled (``pool_budget`` > 0, from
    ``data.mp_workers``) the old 1-core clamp-to-zero is WRONG: the pool
    replaces grain's worker machinery outright — its workers block on a
    queue when idle instead of spinning grain's per-element IPC — so the
    effective count clamps against the POOL's own budget (floor 1).
    Either way the decision is logged once per distinct clamp and
    exposed as the ``input_effective_workers`` gauge.
    """
    if avail is None:
        avail = os.cpu_count() or 1
    if pool_budget > 0:
        bounded = max(1, min(requested, pool_budget)) if requested > 0 \
            else pool_budget
        why = (f"shared-memory pool budget {pool_budget} "
               f"(data.mp_workers; {avail} host core(s))")
    else:
        bounded = max(0, min(requested, avail - 1))
        why = (f"{avail} host core(s); worker processes need a spare "
               "core — 0 = in-process loading")
    if bounded != requested and (requested, bounded) not in _CLAMP_LOGGED:
        _CLAMP_LOGGED.add((requested, bounded))
        import warnings

        warnings.warn(
            f"grain num_workers={requested} clamped to {bounded} ({why})")
    _effective_workers_gauge("grain").set(bounded)
    return bounded


class _BatchIndexSource:
    """Grain source over whole BATCHES of record indices.

    Batching happens HERE — in the source, BEFORE grain's worker
    sharding — which is the load-bearing design choice: grain
    stride-shards the element stream across worker processes and runs
    `gp.Batch` inside each worker, so batches formed by an operation
    are composed of worker-strided subsequences and their composition
    CHANGES with worker_count (and a mid-epoch resume that slices the
    source contiguously reproduces neither the set nor the order the
    uninterrupted run consumed). With one-element-per-batch sources,
    workers stride over batches, grain's deterministic interleave
    restores source order, and batch b is ALWAYS epoch-order slice
    [b*B:(b+1)*B] — invariant to worker_count, exactly what
    epoch(start_batch=) slicing assumes."""

    def __init__(self, order: np.ndarray, batch: int):
        self._order = order
        self._batch = batch

    def __len__(self) -> int:
        return (len(self._order) + self._batch - 1) // self._batch

    def __getitem__(self, b: int) -> np.ndarray:
        return self._order[b * self._batch:(b + 1) * self._batch]


def load_batch_payload(dataset, item_style: bool, train: bool,
                       seed: int, epoch: int, idx: np.ndarray) -> dict:
    """Load ONE host batch under the GRAIN rng-keying convention — the
    single definition shared by grain's MapTransform (in grain worker
    processes or in-process under worker_count=0) and the shared-memory
    decode pool (data/workers.py), so the two process models cannot
    drift byte-wise.

    Batched (get_batch) rng is keyed on (seed, epoch, the batch's FULL
    index tuple) — the full tuple, not idx[0], because weighted sampling
    with replacement can repeat a first element across different
    batches. Item-style records keep per-RECORD keying (seed, epoch,
    record index): each record's augment draws are bit-exact regardless
    of how batches regroup."""
    idx = np.asarray(idx, np.int64)
    # Retry/backoff + the `data.decode` fault point come from the
    # faults package (lazy import: worker processes rebuild their own
    # process-local schedule from the PDTT_FAULTS env var).
    from pytorch_distributed_train_tpu import faults as faults_lib

    # The span feeds span_seconds{name="data.grain.load_batch"} — the
    # decode wait is a scrapable histogram, so the worker_count=0
    # throughput question (ADVICE round 5) is answerable from /metrics
    # instead of re-profiling.
    with _span("data.grain.load_batch", records=int(len(idx))):
        if item_style:
            # Per-record decode fans out over a thread pool: under
            # worker_count=0 the round-5 batched-map restructure had
            # serialized what used to run on grain's read threads (PIL
            # decode releases the GIL). Per-record rng keying is
            # position-free, so thread scheduling cannot perturb
            # reproducibility. Substituted records (decode_with_retry's
            # last resort) keep the keying: record j's rng is always
            # (seed, epoch, j), wherever it lands.
            def _load(i):
                def load(j):
                    faults_lib.maybe_fire("data.decode")
                    return dataset.get_item(
                        int(j), np.random.default_rng(
                            np.random.SeedSequence(
                                (seed, epoch, int(j)))))

                return faults_lib.decode_with_retry(
                    load, int(i), len(dataset))

            items = list(_decode_pool().map(_load, idx))
            return {k: np.stack([it[k] for it in items])
                    for k in items[0]}

        def _load_batch():
            faults_lib.maybe_fire("data.decode")
            rng = np.random.default_rng(np.random.SeedSequence(
                (seed, epoch) + tuple(int(t) for t in idx)))
            return dataset.get_batch(idx, rng, train)

        return faults_lib.retry_call(_load_batch, point="data.decode")


def _make_load_transform(dataset, item_style: bool, train: bool,
                         seed: int, epoch: int):
    """One MapTransform per host BATCH (an index array element).

    get_batch datasets get ONE dataset call per batch — round-5
    profiling (BASELINE.md, tools/grain_profile.py) measured
    ~1.1 ms/record of pure grain machinery in the per-record
    formulation, and batch-of-1 calls starved the native batch decoder
    (native/jpegdec.cpp); whole-batch elements amortize the machinery
    by the batch size and hand the decoder real batches. Load + rng
    semantics live in :func:`load_batch_payload`."""
    import grain.python as gp

    class _LoadBatch(gp.MapTransform):
        def map(self, idx):
            return load_batch_payload(dataset, item_style, train, seed,
                                      epoch, idx)

    return _LoadBatch()


class GrainHostDataLoader:
    """Per-host loader over Grain worker processes."""

    def __init__(self, dataset, data_cfg, *, train: bool,
                 num_hosts: int | None = None, host_id: int | None = None):
        self.dataset = dataset
        self.train = train
        # NOTE: the defaults initialize the device backend (process_count
        # → jax.devices()); host-only callers (benches, tools) must pass
        # num_hosts/host_id explicitly so a wedged accelerator lease can
        # never stall a pure-host data pipeline.
        self.num_hosts = (num_hosts if num_hosts is not None
                          else jax.process_count())
        self.host_id = host_id if host_id is not None else jax.process_index()
        global_batch = data_cfg.batch_size if train else (
            data_cfg.eval_batch_size or data_cfg.batch_size
        )
        if global_batch % self.num_hosts != 0:
            raise ValueError(
                f"global batch {global_batch} not divisible by "
                f"{self.num_hosts} hosts"
            )
        self.host_batch = global_batch // self.num_hosts
        self.seed = data_cfg.seed
        self.shuffle = train and data_cfg.shuffle
        # Shared-memory decode pool (data/workers.py): when enabled it
        # REPLACES grain's worker machinery — the in-process
        # worker_count=0 degenerate mode this loader was clamped into on
        # core-starved hosts — so the effective worker count clamps
        # against the pool's own budget, not cpu_count-1 (ISSUE 12
        # satellite: the grain bounded_workers fix).
        from pytorch_distributed_train_tpu.data import workers as workers_lib

        self._pool_budget = (
            workers_lib.pool_budget(getattr(data_cfg, "mp_workers", 0))
            if workers_lib.available() else 0)
        self.num_workers = bounded_workers(
            data_cfg.num_workers, pool_budget=self._pool_budget)
        self.decode_threads_per_worker = 0
        if self._pool_budget > 0 and getattr(dataset, "is_item_style",
                                             False):
            # mp pool + grain ITEM-style decode: each forked worker also
            # fans out a PIL decode thread pool. Uncapped that composed
            # pathologically (LKG pil_grain_mp8: 424 img/s vs plain
            # threads' 444) — workers.python_thread_budget now clamps
            # each worker to its core share; surface the decision once
            # (log + gauge) so the throughput math is inspectable.
            avail = os.cpu_count() or 1
            per = workers_lib.pool_decode_threads(self.num_workers)
            self.decode_threads_per_worker = per
            total = per * self.num_workers
            from pytorch_distributed_train_tpu.obs.registry import (
                get_registry,
            )

            get_registry().gauge(
                "input_decode_threads", labels={"loader": "grain"},
                help="PIL decode threads per forked mp pool worker "
                     "after the core-share clamp").set(per)
            key = ("decode-threads", self.num_workers, per)
            if key not in _CLAMP_LOGGED:
                _CLAMP_LOGGED.add(key)
                import warnings

                warnings.warn(
                    f"grain + data.mp_workers item decode: "
                    f"{self.num_workers} worker(s) x {per} PIL decode "
                    f"thread(s) = {total} on {avail} host core(s) "
                    "(per-worker pool clamped to the core share — the "
                    "pil_grain_mp8 oversubscription fix)")
        self.mp_slots = getattr(data_cfg, "mp_slots", 0)
        self._mp_pool = None
        self.read_buffer = max(2, data_cfg.prefetch)
        self.weighted = None
        if train and getattr(data_cfg, "weighted_sampling", ""):
            # torch WeightedRandomSampler parity under the PROCESS loader
            # too: the weighted draw replaces Grain's uniform IndexSampler
            # by using the epoch's record order (host-sharded, seed+epoch
            # deterministic — data/sampler.py) as an explicit array
            # source, the same mechanism the mid-epoch resume path uses.
            # Augment-rng nuance vs the threads loader, per transform
            # shape: ITEM-style records drawn twice in an epoch (with
            # replacement) reuse the same per-record rng where the
            # threads loader draws fresh; BATCHED get_batch loads key
            # on the batch's full index tuple, so only an entirely
            # repeated batch repeats its draws. Construction/validation
            # shared with HostDataLoader (sampler.make_weighted_sampler).
            from pytorch_distributed_train_tpu.data.sampler import (
                make_weighted_sampler,
            )

            self.weighted = make_weighted_sampler(
                dataset, data_cfg, self.num_hosts, self.host_id)

    @property
    def steps_per_epoch(self) -> int:
        per_host = len(self.dataset) // self.num_hosts
        if self.train:
            return per_host // self.host_batch
        return (per_host + self.host_batch - 1) // self.host_batch

    def _sampler(self, epoch: int):
        import grain.python as gp

        # UNSHARDED on purpose (elastic resharding, docs/elastic.md):
        # grain's ShardOptions splits the record range into CONTIGUOUS
        # blocks and shuffles within each, so the set of records behind
        # global batch b would change with shard_count — a gang that
        # shrinks mid-epoch could then replay or skip records. One
        # GLOBAL shuffle (seed+epoch) with hosts taking strided
        # positions keeps the union of all hosts' batch b equal to the
        # same global slice at ANY world size, which is exactly the
        # invariant the mid-epoch start_batch fast-forward assumes.
        return gp.IndexSampler(
            num_records=len(self.dataset),
            shard_options=gp.NoSharding(),
            shuffle=self.shuffle,
            # per-epoch reshuffle ≡ DistributedSampler.set_epoch (C16)
            seed=self.seed + epoch,
            num_epochs=1,
        )

    def _epoch_order(self, epoch: int) -> np.ndarray:
        """This host's record order for the epoch, as one int64 array.

        Weighted sampling has it materialized already; otherwise it is
        enumerated from grain's IndexSampler (pure index math, no IO —
        ~O(n) python at iterator construction, overlapped with compile
        by the producer thread). An explicit order array is what lets
        batching live in the SOURCE (see _BatchIndexSource) and resume
        slice at exact batch boundaries. Host h takes positions
        h, h+world, ... of the GLOBAL shuffled stream (the
        DistributedSampler stride, C16), so the per-host order is a
        pure function of (seed, epoch, world, host) — shard_count
        changes reshard the SAME epoch-global order."""
        if self.weighted is not None:
            self.weighted.set_epoch(epoch)
            n = self.steps_per_epoch * self.host_batch
            return np.asarray(self.weighted.indices()[:n], np.int64)
        sampler = self._sampler(epoch)
        n = min(self.steps_per_epoch * self.host_batch,
                len(self.dataset) // self.num_hosts)
        return np.fromiter(
            (sampler[self.host_id + k * self.num_hosts].record_key
             for k in range(n)), np.int64, count=n)

    def close(self) -> None:
        """Release the shared-memory pool (bench/tests)."""
        if self._mp_pool is not None:
            self._mp_pool.close()
            self._mp_pool = None

    def _pad_tail(self, out: dict) -> dict:
        short = self.host_batch - len(next(iter(out.values())))
        if short > 0:
            # Pad the tail batch by wrapping — SPMD needs static shapes
            # (same invariant as HostDataLoader's eval-tail wrap).
            out = {
                k: np.concatenate(
                    [v, np.tile(v, (short // len(v) + 1,)
                                + (1,) * (v.ndim - 1))[:short]]
                )
                for k, v in out.items()
            }
        return out

    def _pool_load(self, task) -> dict:
        """One (epoch, idx-array) pool task → batch dict, under grain's
        rng-keying convention (load_batch_payload) — runs inside a
        forked decode worker; byte-identical to the grain path."""
        epoch, idx = task
        return load_batch_payload(
            self.dataset, getattr(self.dataset, "is_item_style", False),
            self.train, self.seed, epoch, idx)

    def _epoch_via_pool(self, epoch: int,
                        order: np.ndarray) -> Iterator[dict]:
        """Shared-memory pool path: same epoch-order batch slices as the
        grain source (_BatchIndexSource semantics), decoded in N forked
        workers. Batch b is ALWAYS epoch-order slice [b*B:(b+1)*B] —
        invariant to the worker count, resume-exact."""
        if self._mp_pool is None:
            from pytorch_distributed_train_tpu.data import (
                workers as workers_lib,
            )

            self._mp_pool = workers_lib.SharedMemoryWorkerPool(
                self._pool_load, self.num_workers, slots=self.mp_slots,
                post_fork=lambda: workers_lib.reset_thread_local_state(
                    self.dataset))
        n_batches = (len(order) + self.host_batch - 1) // self.host_batch
        tasks = ((epoch, order[b * self.host_batch:
                               (b + 1) * self.host_batch])
                 for b in range(n_batches))
        for out in self._mp_pool.run(tasks):
            yield self._pad_tail(
                {k: np.asarray(v) for k, v in out.items()})

    def epoch(self, epoch: int, start_batch: int = 0) -> Iterator[dict]:
        order = self._epoch_order(epoch)[start_batch * self.host_batch:]
        if self._pool_budget > 0:
            return self._epoch_via_pool(epoch, order)
        return self._epoch_grain(epoch, order)

    def _epoch_grain(self, epoch: int, order: np.ndarray) -> Iterator[dict]:
        import grain.python as gp

        source = _BatchIndexSource(order, self.host_batch)
        order_sampler = gp.IndexSampler(
            num_records=len(source), shuffle=False,
            seed=self.seed + epoch, num_epochs=1,
            shard_options=gp.NoSharding(),
        )
        ops = [_make_load_transform(
            self.dataset, getattr(self.dataset, "is_item_style", False),
            self.train, self.seed, epoch)]
        read = gp.ReadOptions(
            num_threads=max(1, min(16, self.read_buffer)),
            prefetch_buffer_size=self.read_buffer)
        loader = gp.DataLoader(
            data_source=source,
            sampler=order_sampler,
            operations=ops,
            worker_count=self.num_workers,
            read_options=read,
        )
        # Stage attribution (obs/perf.py): with worker PROCESSES the
        # decode/augment stage timers fire inside the workers where this
        # process can't see them, so the host-side wait on the IPC
        # stream is attributed to `read` (fetching finished records).
        # With worker_count=0 the map runs inline in next() and the
        # dataset's own read/decode/augment timers already cover it —
        # timing the wait too would double-count every stage.
        from pytorch_distributed_train_tpu.obs import perf as perf_lib

        it = iter(loader)
        _done = object()
        while True:
            if self.num_workers > 0:
                with perf_lib.stage("read"):
                    batch = next(it, _done)
            else:
                batch = next(it, _done)
            if batch is _done:
                break
            yield self._pad_tail({k: np.asarray(v) for k, v in batch.items()})
