"""Grain-backed host loader — the multiprocess alternative to the threaded
HostDataLoader (SURVEY C17: torch's DataLoader runs worker *processes*,
torch:utils/data/_utils/worker.py:244; Grain is the JAX-ecosystem loader
with the same process-pool design).

Selected via ``DataConfig.loader = "grain"``. Duck-types HostDataLoader
(``steps_per_epoch``, ``epoch(epoch, start_batch)``) so the rest of the
input pipeline — producer thread, HBM prefetch, sync checks — is shared.

Reuses the datasets unchanged, with the transform SHAPE picked per
dataset style (round-5 restructure — BASELINE.md "grain gap"):
item-style datasets map per record through ``get_item`` then batch;
``get_batch`` datasets batch the CHEAP index stream FIRST and make ONE
``get_batch`` call per host batch — grain's per-element machinery
amortizes by the batch size and the native batch decoder
(native/jpegdec.cpp) gets real batches. Augment randomness does NOT
use Grain's sampler-position rng: item-style records key their rng on
(seed, epoch, record index) and batched loads on (seed, epoch, the
batch's full index tuple) — both make mid-epoch resume draws bit-exact
(resumes slice at batch boundaries, so batch composition is identical
to the uninterrupted epoch; see _LoadRecord/_LoadBatch).

Sharding/shuffle semantics mirror DistributedSampler (C16): per-epoch
reseeded shuffle, host-sharded with drop_remainder — though the shuffle
permutation itself is Grain's, not byte-identical to data/sampler.py.
"""

from __future__ import annotations

import os
from typing import Iterator

import jax
import numpy as np


def bounded_workers(requested: int, avail: int | None = None) -> int:
    """Cap Grain worker PROCESSES by what the host can actually run.

    Worker processes exist to escape the GIL onto OTHER cores
    (torch:utils/data/_utils/worker.py:244 — same rationale); on a host
    with no core to spare they only add spawn+IPC contention against the
    consumer. Measured on this repo's 1-core sandbox: the uncapped
    process arm produced no batch within 550 s (BASELINE.md r2 "DNF"),
    while worker_count=0 (in-process loading, Grain's supported
    degenerate mode) streams fine. Cap = cpu_count - 1 (one core stays
    with the consumer/train loop), never more than requested.
    """
    if avail is None:
        avail = os.cpu_count() or 1
    bounded = max(0, min(requested, avail - 1))
    if bounded < requested:
        # Say so: a configured worker count silently collapsing to
        # in-process loading would read as an unexplained throughput drop.
        import warnings

        warnings.warn(
            f"grain num_workers={requested} clamped to {bounded} "
            f"({avail} host core(s); worker processes need a spare core "
            "— 0 = in-process loading)")
    return bounded


class _IndexSource:
    """Grain source yielding record indices; transforms do the real work
    (keeps dataset objects out of the pickled source when possible)."""

    def __init__(self, n: int):
        self._n = n

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i: int) -> int:
        return int(i)


def _make_load_transform(dataset, train: bool, seed: int, epoch: int):
    import grain.python as gp

    class _LoadRecord(gp.MapTransform):
        """Augment rng keyed on (seed, epoch, RECORD index) — not Grain's
        sampler-position rng — so a mid-epoch resume (which re-enumerates
        the tail at shifted positions) reproduces the exact per-record
        draws of the uninterrupted epoch."""

        def map(self, i):
            rng = np.random.default_rng(
                np.random.SeedSequence((seed, epoch, int(i))))
            return dataset.get_item(int(i), rng)

    return _LoadRecord()


def _make_batch_load_transform(dataset, train: bool, seed: int,
                               epoch: int):
    """Batched load for get_batch-style datasets: ONE dataset call per
    host batch instead of per record.

    Round-5 profiling (BASELINE.md, tools/grain_profile.py): the
    per-record formulation cost ~1.1 ms/record of pure grain machinery
    on this host — every record paid the map->stats->batch iterator
    chain and a read-thread handoff, and the NATIVE batch decoder
    (native/jpegdec.cpp) was reduced to batch-of-1 calls. Batching the
    cheap index stream FIRST amortizes all of it by the batch size and
    hands the native decoder real batches (its parallel_for threads
    engage again on multi-core hosts).

    Resume exactness is preserved at the granularity resumes actually
    happen: epoch(start_batch=) slices at BATCH boundaries, so batch
    composition is identical to the uninterrupted epoch and the rng —
    keyed on (seed, epoch, the batch's FULL index tuple) — draws
    identically. (The old per-record keying was stricter than any
    resume point could observe; the batch-granular convention also
    matches the threads loader's.)"""
    import grain.python as gp

    class _LoadBatch(gp.MapTransform):
        def map(self, idx):
            idx = np.asarray(idx, np.int64)
            # key on the FULL index tuple, not idx[0]: weighted
            # sampling with replacement can put the same record first
            # in two different batches, and a first-index key would
            # give both batches element-wise identical augmentation
            # streams — whole-batch correlation. The full-composition
            # key collides only when the entire batch repeats.
            rng = np.random.default_rng(np.random.SeedSequence(
                (seed, epoch) + tuple(int(t) for t in idx)))
            return dataset.get_batch(idx, rng, train)

    return _LoadBatch()


class GrainHostDataLoader:
    """Per-host loader over Grain worker processes."""

    def __init__(self, dataset, data_cfg, *, train: bool,
                 num_hosts: int | None = None, host_id: int | None = None):
        self.dataset = dataset
        self.train = train
        # NOTE: the defaults initialize the device backend (process_count
        # → jax.devices()); host-only callers (benches, tools) must pass
        # num_hosts/host_id explicitly so a wedged accelerator lease can
        # never stall a pure-host data pipeline.
        self.num_hosts = (num_hosts if num_hosts is not None
                          else jax.process_count())
        self.host_id = host_id if host_id is not None else jax.process_index()
        global_batch = data_cfg.batch_size if train else (
            data_cfg.eval_batch_size or data_cfg.batch_size
        )
        if global_batch % self.num_hosts != 0:
            raise ValueError(
                f"global batch {global_batch} not divisible by "
                f"{self.num_hosts} hosts"
            )
        self.host_batch = global_batch // self.num_hosts
        self.seed = data_cfg.seed
        self.shuffle = train and data_cfg.shuffle
        self.num_workers = bounded_workers(data_cfg.num_workers)
        self.read_buffer = max(2, data_cfg.prefetch)
        self.weighted = None
        if train and getattr(data_cfg, "weighted_sampling", ""):
            # torch WeightedRandomSampler parity under the PROCESS loader
            # too: the weighted draw replaces Grain's uniform IndexSampler
            # by using the epoch's record order (host-sharded, seed+epoch
            # deterministic — data/sampler.py) as an explicit array
            # source, the same mechanism the mid-epoch resume path uses.
            # Augment-rng nuance vs the threads loader, per transform
            # shape: ITEM-style records drawn twice in an epoch (with
            # replacement) reuse the same per-record rng where the
            # threads loader draws fresh; BATCHED get_batch loads key
            # on the batch's full index tuple, so only an entirely
            # repeated batch repeats its draws. Construction/validation
            # shared with HostDataLoader (sampler.make_weighted_sampler).
            from pytorch_distributed_train_tpu.data.sampler import (
                make_weighted_sampler,
            )

            self.weighted = make_weighted_sampler(
                dataset, data_cfg, self.num_hosts, self.host_id)

    @property
    def steps_per_epoch(self) -> int:
        per_host = len(self.dataset) // self.num_hosts
        if self.train:
            return per_host // self.host_batch
        return (per_host + self.host_batch - 1) // self.host_batch

    def _sampler(self, epoch: int):
        import grain.python as gp

        return gp.IndexSampler(
            num_records=len(self.dataset),
            shard_options=gp.ShardOptions(
                shard_index=self.host_id, shard_count=self.num_hosts,
                drop_remainder=True,
            ),
            shuffle=self.shuffle,
            # per-epoch reshuffle ≡ DistributedSampler.set_epoch (C16)
            seed=self.seed + epoch,
            num_epochs=1,
        )

    def epoch(self, epoch: int, start_batch: int = 0) -> Iterator[dict]:
        import grain.python as gp

        if self.weighted is not None:
            self.weighted.set_epoch(epoch)
            n = self.steps_per_epoch * self.host_batch
            # ndarray slice straight into grain (len/__getitem__ suffice;
            # the load transform ints each element): no per-epoch
            # million-object list build, compact worker pickles.
            source: object = self.weighted.indices()[
                start_batch * self.host_batch:n]
            order_sampler = gp.IndexSampler(
                num_records=len(source), shuffle=False,
                seed=self.seed + epoch, num_epochs=1,
                shard_options=gp.NoSharding(),
            )
        elif start_batch > 0:
            # Mid-epoch resume: enumerate the epoch's record order from the
            # sampler (pure index math), slice, and run a sequential pass —
            # O(skip) index reads instead of materializing skipped batches
            # through the workers. Data order AND augment draws match the
            # uninterrupted epoch (the load transform keys its rng on the
            # record index travelling through the sliced source).
            sampler = self._sampler(epoch)
            n = min(self.steps_per_epoch * self.host_batch,
                    len(self.dataset) // self.num_hosts)
            ids = [int(sampler[i].record_key)
                   for i in range(start_batch * self.host_batch, n)]
            source: object = ids
            order_sampler = gp.IndexSampler(
                num_records=len(ids), shuffle=False,
                seed=self.seed + epoch, num_epochs=1,
                shard_options=gp.NoSharding(),
            )
        else:
            source = _IndexSource(len(self.dataset))
            order_sampler = self._sampler(epoch)
        if getattr(self.dataset, "is_item_style", False):
            # per-record load (PIL/item datasets), then batch
            ops = [
                _make_load_transform(self.dataset, self.train,
                                     self.seed, epoch),
                gp.Batch(batch_size=self.host_batch,
                         drop_remainder=False),
            ]
            read = gp.ReadOptions(
                num_threads=max(1, min(16, self.read_buffer)),
                prefetch_buffer_size=self.read_buffer)
        else:
            # get_batch datasets: batch the CHEAP index stream first,
            # then one dataset call per batch (_make_batch_load_
            # transform docstring has the round-5 profiling story).
            # Elements crossing grain's read threads are ints, so a
            # deeper prefetch costs nothing and keeps the consumer fed.
            ops = [
                gp.Batch(batch_size=self.host_batch,
                         drop_remainder=False),
                _make_batch_load_transform(self.dataset, self.train,
                                           self.seed, epoch),
            ]
            read = gp.ReadOptions(
                num_threads=max(1, min(16, self.read_buffer)),
                prefetch_buffer_size=max(
                    self.read_buffer, 2 * self.host_batch))
        loader = gp.DataLoader(
            data_source=source,
            sampler=order_sampler,
            operations=ops,
            worker_count=self.num_workers,
            read_options=read,
        )
        n_steps = self.steps_per_epoch - start_batch
        for b, batch in enumerate(loader):
            if b >= n_steps:
                break
            out = {k: np.asarray(v) for k, v in batch.items()}
            short = self.host_batch - len(next(iter(out.values())))
            if short > 0:
                # Pad the tail batch by wrapping — SPMD needs static shapes
                # (same invariant as HostDataLoader's eval-tail wrap).
                out = {
                    k: np.concatenate(
                        [v, np.tile(v, (short // len(v) + 1,)
                                    + (1,) * (v.ndim - 1))[:short]]
                    )
                    for k, v in out.items()
                }
            yield out
