"""Host-side RandAugment (Cubuk et al. 2020), torchvision semantics.

The reference-era ImageNet recipes (torchvision ``--auto-augment ra``)
apply RandAugment between RandomHorizontalFlip and normalization. This is
inherently per-image, branchy, uint8 work — exactly what should stay on the
host CPU (it would recompile per op-combination under jit), so unlike
MixUp/CutMix (ops/mixup.py, device-side) it lives in the data pipeline and
runs inside the loader's worker threads (PIL releases the GIL).

Op space, magnitude binning (31 bins), signed-ops coin flip, and the
affine conventions mirror ``torchvision.transforms.RandAugment``
(num_ops=2, magnitude=9 defaults). Randomness comes from the caller's
seeded ``np.random.Generator`` — same generator discipline as the rest of
the pipeline, so epochs are reproducible and resume-stable.
"""

from __future__ import annotations

import numpy as np

_BINS = 31


def _enhance(factor_cls):
    def apply(im, mag, _rng):
        from PIL import ImageEnhance

        return getattr(ImageEnhance, factor_cls)(im).enhance(1.0 + mag)

    return apply


def _shear_x(im, mag, _rng):
    from PIL import Image

    # torchvision shears about the top-left corner with nearest resampling.
    return im.transform(im.size, Image.AFFINE, (1, mag, 0, 0, 1, 0),
                        Image.NEAREST, fillcolor=0)


def _shear_y(im, mag, _rng):
    from PIL import Image

    return im.transform(im.size, Image.AFFINE, (1, 0, 0, mag, 1, 0),
                        Image.NEAREST, fillcolor=0)


def _translate_x(im, mag, _rng):
    from PIL import Image

    return im.transform(im.size, Image.AFFINE, (1, 0, mag, 0, 1, 0),
                        Image.NEAREST, fillcolor=0)


def _translate_y(im, mag, _rng):
    from PIL import Image

    return im.transform(im.size, Image.AFFINE, (1, 0, 0, 0, 1, mag),
                        Image.NEAREST, fillcolor=0)


def _rotate(im, mag, _rng):
    from PIL import Image

    return im.rotate(mag, Image.NEAREST, fillcolor=0)


def _posterize(im, mag, _rng):
    from PIL import ImageOps

    return ImageOps.posterize(im, int(mag))


def _solarize(im, mag, _rng):
    from PIL import ImageOps

    return ImageOps.solarize(im, int(mag))


def _autocontrast(im, _mag, _rng):
    from PIL import ImageOps

    return ImageOps.autocontrast(im)


def _equalize(im, _mag, _rng):
    from PIL import ImageOps

    return ImageOps.equalize(im)


def _identity(im, _mag, _rng):
    return im


def _op_table(width: int, height: int):
    """(name, apply_fn, magnitudes[31] or None, signed) rows — the
    torchvision ``_augmentation_space`` table for a width×height image
    (translate bins scale with the translated axis, as torchvision's do)."""
    lin = np.linspace
    return [
        ("Identity", _identity, None, False),
        ("ShearX", _shear_x, lin(0.0, 0.3, _BINS), True),
        ("ShearY", _shear_y, lin(0.0, 0.3, _BINS), True),
        ("TranslateX", _translate_x, lin(0.0, 150.0 / 331.0 * width, _BINS), True),
        ("TranslateY", _translate_y, lin(0.0, 150.0 / 331.0 * height, _BINS), True),
        ("Rotate", _rotate, lin(0.0, 30.0, _BINS), True),
        ("Brightness", _enhance("Brightness"), lin(0.0, 0.9, _BINS), True),
        ("Color", _enhance("Color"), lin(0.0, 0.9, _BINS), True),
        ("Contrast", _enhance("Contrast"), lin(0.0, 0.9, _BINS), True),
        ("Sharpness", _enhance("Sharpness"), lin(0.0, 0.9, _BINS), True),
        ("Posterize", _posterize,
         8 - np.round(np.arange(_BINS) / ((_BINS - 1) / 4)), False),
        ("Solarize", _solarize, lin(255.0, 0.0, _BINS), False),
        ("AutoContrast", _autocontrast, None, False),
        ("Equalize", _equalize, None, False),
    ]


class RandAugment:
    """num_ops uniformly-chosen ops at a fixed magnitude bin, per image."""

    def __init__(self, num_ops: int = 2, magnitude: int = 9):
        if not 0 <= magnitude < _BINS:
            raise ValueError(f"magnitude must be in [0, {_BINS - 1}]")
        self.num_ops = num_ops
        self.magnitude = magnitude
        self._tables: dict[tuple[int, int], list] = {}  # per (W, H) op table

    def __getstate__(self):
        # The op-table cache holds closures (unpicklable); grain's worker
        # processes pickle the dataset that owns this transform. Rebuilt
        # lazily on first use.
        state = self.__dict__.copy()
        state["_tables"] = {}
        return state

    def __call__(self, im, rng: np.random.Generator):
        table = self._tables.get(im.size)
        if table is None:
            table = self._tables[im.size] = _op_table(*im.size)
        for _ in range(self.num_ops):
            name, fn, mags, signed = table[int(rng.integers(len(table)))]
            mag = float(mags[self.magnitude]) if mags is not None else 0.0
            if signed and rng.random() < 0.5:
                mag = -mag
            im = fn(im, mag, rng)
        return im


def apply_randaugment_u8(img_u8: np.ndarray, aug: RandAugment,
                         rng: np.random.Generator) -> np.ndarray:
    """Array-dataset adapter: HWC uint8 → RandAugment → HWC uint8."""
    from PIL import Image

    return np.asarray(aug(Image.fromarray(img_u8), rng), np.uint8)
