"""Datasets for the acceptance matrix (BASELINE.json:7-11).

Two dataset shapes, mirroring the torch Dataset split the reference loader
consumes (map-style, torch:utils/data/dataloader.py):

- **ArrayDataset** — whole dataset in host RAM as numpy arrays; `get_batch`
  is one fancy-index + vectorized augment (CIFAR-10, synthetic).
- **ItemDataset** — per-item `get_item(i)` (JPEG decode + augment for
  ImageNet folders); the loader maps it over a thread pool, standing in for
  DataLoader's worker processes (SURVEY C17) — threads suffice because
  PIL/numpy release the GIL in the decode/resize hot path.

All image batches are NHWC float32, normalized; the device-side bf16 cast
happens inside the jitted step (precision policy, SURVEY C18).
"""

from __future__ import annotations

import os
import pickle
from typing import Iterable

import numpy as np

# Standard normalization constants (the reference-era torchvision recipe).
CIFAR_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR_STD = np.array([0.2470, 0.2435, 0.2616], np.float32)
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


class ArrayDataset:
    """In-RAM dataset: dict of equal-length numpy arrays."""

    is_item_style = False

    def __init__(self, arrays: dict[str, np.ndarray]):
        lens = {k: len(v) for k, v in arrays.items()}
        if len(set(lens.values())) != 1:
            raise ValueError(f"ragged arrays: {lens}")
        self.arrays = arrays

    def __len__(self) -> int:
        return len(next(iter(self.arrays.values())))

    def get_batch(self, idx: np.ndarray, rng: np.random.Generator, train: bool) -> dict:
        return {k: v[idx] for k, v in self.arrays.items()}


def _crop_flip(images: np.ndarray, pad: int, ys, xs, flips) -> np.ndarray:
    """Reflect-pad random crop + hflip with precomputed draws — the numpy
    reference for the native kernel (imgops.augment_batch minus normalize)."""
    B, H, W, _ = images.shape
    padded = np.pad(images, ((0, 0), (pad,) * 2, (pad,) * 2, (0, 0)),
                    mode="reflect")
    out = np.empty_like(images)
    for i in range(B):
        img = padded[i, ys[i]: ys[i] + H, xs[i]: xs[i] + W]
        out[i] = img[:, ::-1] if flips[i] else img
    return out


class U8ImageDataset(ArrayDataset):
    """uint8 image storage + fused native augment/normalize (native/imgops).

    Keeps the dataset in RAM at 1/4 the float32 footprint and runs the
    reflect-pad crop + hflip + u8→f32 normalize as ONE multithreaded C++
    pass per batch (SURVEY C17 native equivalent). Falls back to the numpy
    path when the native build is unavailable — batch values are identical
    either way (both implement reflect-101 padding then (x/255-mean)/std).
    """

    def __init__(self, images_u8: np.ndarray | None, labels: np.ndarray,
                 mean: np.ndarray, std: np.ndarray, augment: bool,
                 pad: int = 4, randaugment=None, raw_u8: bool = False):
        # images_u8=None is the storage-elsewhere subclass hook (the
        # packed cache mmaps its pixels): _read_images is overridden and
        # only labels live in self.arrays.
        arrays = {"label": labels}
        if images_u8 is not None:
            arrays["image"] = images_u8
        super().__init__(arrays)
        self.mean, self.std = mean, std
        self.do_augment = augment
        self.pad = pad
        self.randaugment = randaugment if augment else None
        # raw_u8 (data.device_augment): ship uint8 pixels untouched —
        # crop/flip/RandAugment/normalize move into the jitted step
        # (ops/device_augment.py), so the host's augment share collapses
        # to the fancy-index read.
        self.raw_u8 = raw_u8
        self._ra_pool = None

    def __getstate__(self):
        # Thread pools don't pickle (grain's worker processes pickle the
        # dataset); it is rebuilt lazily in the worker.
        state = self.__dict__.copy()
        state["_ra_pool"] = None
        return state

    def _randaugment_batch(self, imgs_u8: np.ndarray, rng) -> np.ndarray:
        """RandAugment each image on a thread pool (PIL releases the GIL;
        a serial loop here would stall the single producer thread and make
        training input-bound). Per-image seeds are drawn up-front from the
        batch rng, so the result is deterministic regardless of thread
        scheduling."""
        from concurrent.futures import ThreadPoolExecutor

        from pytorch_distributed_train_tpu.data.augment import (
            apply_randaugment_u8,
        )

        seeds = rng.integers(np.iinfo(np.int64).max, size=len(imgs_u8))
        if len(imgs_u8) <= 2:
            # grain's per-record path calls with a single image — skip the
            # pool (a 16-thread executor per worker process for zero
            # parallelism otherwise).
            return np.stack([
                apply_randaugment_u8(im, self.randaugment,
                                     np.random.default_rng(s))
                for im, s in zip(imgs_u8, seeds)
            ])
        if self._ra_pool is None:
            self._ra_pool = ThreadPoolExecutor(
                max_workers=min(16, os.cpu_count() or 4))
        return np.stack(list(self._ra_pool.map(
            lambda args: apply_randaugment_u8(
                args[0], self.randaugment, np.random.default_rng(args[1])),
            zip(imgs_u8, seeds),
        )))

    def _read_images(self, idx) -> np.ndarray:
        """Pixel gather for a batch — overridden by the packed cache
        (mmap'd strided read instead of an in-RAM fancy index)."""
        return self.arrays["image"][idx]

    def get_batch(self, idx, rng, train):
        from pytorch_distributed_train_tpu.native import imgops
        from pytorch_distributed_train_tpu.obs.perf import stage

        with stage("read"):
            imgs = self._read_images(idx)
        if self.raw_u8:
            # Device-side augmentation path: the read IS the whole host
            # cost; pixels leave as uint8 (4x less h2d traffic than the
            # normalized f32 batch they replace).
            return {"image": np.ascontiguousarray(imgs),
                    "label": self.arrays["label"][idx]}
        B, H, W, C = imgs.shape
        with stage("augment"):
            return self._augment_batch(imgs, idx, rng, train, B, imgops)

    def _augment_batch(self, imgs, idx, rng, train, B, imgops):
        if train and self.do_augment:
            ys = rng.integers(0, 2 * self.pad + 1, size=B)
            xs = rng.integers(0, 2 * self.pad + 1, size=B)
            flips = rng.random(B) < 0.5
            if self.randaugment is not None:
                # torchvision recipe order: crop → flip → RandAugment →
                # normalize. RandAugment needs uint8 pixels, so the fused
                # native crop+normalize pass can't be used; crop/flip on u8,
                # augment, then normalize (native when available).
                cropped = _crop_flip(imgs, self.pad, ys, xs, flips)
                auged = self._randaugment_batch(cropped, rng)
                if imgops.available():
                    out = imgops.normalize_batch(auged, self.mean, self.std)
                else:
                    out = (auged.astype(np.float32) / 255.0 - self.mean) / self.std
            elif imgops.available():
                out = imgops.augment_batch(
                    imgs, self.pad, ys, xs, flips, self.mean, self.std)
            else:
                out = _crop_flip(imgs.astype(np.float32), self.pad, ys, xs,
                                 flips)
                out = (out / 255.0 - self.mean) / self.std
        elif imgops.available():
            out = imgops.normalize_batch(imgs, self.mean, self.std)
        else:
            out = (imgs.astype(np.float32) / 255.0 - self.mean) / self.std
        return {"image": out, "label": self.arrays["label"][idx]}


# ------------------------------------------------------------------ CIFAR-10

def load_cifar10(data_dir: str, train: bool, randaugment=None) -> ArrayDataset:
    """Reads the standard python-pickle CIFAR-10 batches (cifar-10-batches-py).

    The reference's config 1 dataset (BASELINE.json:7). Falls back to a
    deterministic synthetic stand-in when no data ships in the sandbox, so
    the preset stays runnable end-to-end.
    """
    base = _find_cifar_dir(data_dir)
    if base is None:
        return synthetic_images(50000 if train else 10000, 32, 10, seed=0 if train else 1)
    files = (
        [f"data_batch_{i}" for i in range(1, 6)] if train else ["test_batch"]
    )
    xs, ys = [], []
    for f in files:
        with open(os.path.join(base, f), "rb") as fh:
            d = pickle.load(fh, encoding="bytes")
        xs.append(d[b"data"])
        ys.append(np.asarray(d[b"labels"], np.int32))
    x = np.ascontiguousarray(
        np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    )  # NHWC uint8 — normalization is fused into the per-batch native pass
    y = np.concatenate(ys)
    return U8ImageDataset(x, y, CIFAR_MEAN, CIFAR_STD, augment=train,
                          randaugment=randaugment)


def _find_cifar_dir(data_dir: str) -> str | None:
    if not data_dir:
        return None
    for cand in (data_dir, os.path.join(data_dir, "cifar-10-batches-py")):
        if os.path.exists(os.path.join(cand, "data_batch_1")):
            return cand
    return None


# ---------------------------------------------------------------- synthetic

def synthetic_images(size: int, image_size: int, num_classes: int, seed: int = 0) -> ArrayDataset:
    """Deterministic fake image classification data (throughput benches and
    the sandbox fallback — no augment, already 'normalized')."""
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((size, image_size, image_size, 3), np.float32)
    y = rng.integers(0, num_classes, size=size).astype(np.int32)
    return ArrayDataset({"image": x, "label": y})


def synthetic_lm(size: int, seq_len: int, vocab_size: int, seed: int = 0) -> ArrayDataset:
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, vocab_size, size=(size, seq_len)).astype(np.int32)
    return ArrayDataset({"input_ids": ids})


def synthetic_dpo(size: int, seq_len: int, vocab_size: int,
                  prompt_len: int | None = None,
                  seed: int = 0) -> ArrayDataset:
    """Random preference pairs for DPO (losses.make_dpo_loss): each row
    holds a shared prompt followed by two different continuations,
    ``input_ids`` (2, S) stacked [chosen, rejected], ``loss_mask``
    marking the continuation positions."""
    rng = np.random.default_rng(seed)
    p = prompt_len if prompt_len is not None else seq_len // 2
    prompt = rng.integers(0, vocab_size, (size, 1, p))
    conts = rng.integers(0, vocab_size, (size, 2, seq_len - p))
    ids = np.concatenate(
        [np.broadcast_to(prompt, (size, 2, p)), conts], axis=2)
    mask = np.zeros((size, 2, seq_len), np.float32)
    mask[:, :, p:] = 1.0
    return ArrayDataset({"input_ids": ids.astype(np.int32),
                         "loss_mask": mask})


def synthetic_seq2seq(size: int, src_len: int, tgt_len: int,
                      vocab_size: int, seed: int = 0) -> ArrayDataset:
    """Random source/target pairs in the T5 convention:
    decoder_input_ids = labels shifted right with a 0 start token
    (HF `_shift_right`; id 0 is T5's pad/decoder-start)."""
    rng = np.random.default_rng(seed)
    src = rng.integers(1, vocab_size, size=(size, src_len)).astype(np.int32)
    labels = rng.integers(1, vocab_size,
                          size=(size, tgt_len)).astype(np.int32)
    dec_in = np.concatenate(
        [np.zeros((size, 1), np.int32), labels[:, :-1]], axis=1)
    return ArrayDataset({"input_ids": src, "decoder_input_ids": dec_in,
                         "labels": labels})


class MLMDataset(ArrayDataset):
    """Token sequences + BERT-style dynamic masking applied at batch time.

    Masking follows the original recipe the reference's config 4 targets
    (BASELINE.json:10): select `mlm_prob` of tokens; 80% → [MASK], 10% →
    random token, 10% → unchanged. Labels carry original ids everywhere;
    `label_weights` marks the selected positions (static shapes — see
    losses.mlm_xent).
    """

    def __init__(self, input_ids: np.ndarray, attention_mask: np.ndarray,
                 vocab_size: int, mlm_prob: float = 0.15, mask_id: int = 103):
        super().__init__({"input_ids": input_ids, "attention_mask": attention_mask})
        self.vocab_size = vocab_size
        self.mlm_prob = mlm_prob
        self.mask_id = mask_id

    def get_batch(self, idx, rng, train):
        ids = self.arrays["input_ids"][idx]
        mask = self.arrays["attention_mask"][idx]
        labels = ids.copy()
        B, S = ids.shape
        sel = (rng.random((B, S)) < self.mlm_prob) & (mask > 0)
        action = rng.random((B, S))
        masked = ids.copy()
        masked[sel & (action < 0.8)] = self.mask_id
        rand_pos = sel & (action >= 0.8) & (action < 0.9)
        masked[rand_pos] = rng.integers(
            0, self.vocab_size, size=int(rand_pos.sum())
        ).astype(ids.dtype)
        return {
            "input_ids": masked,
            "attention_mask": mask,
            "labels": labels,
            "label_weights": sel.astype(np.float32),
        }


def synthetic_mlm(size: int, seq_len: int, vocab_size: int, mlm_prob: float,
                  seed: int = 0) -> MLMDataset:
    rng = np.random.default_rng(seed)
    low = min(200, vocab_size // 2)  # skip the "special token" id range
    ids = rng.integers(low, vocab_size, size=(size, seq_len)).astype(np.int32)
    mask = np.ones_like(ids)
    return MLMDataset(ids, mask, vocab_size, mlm_prob)


# ------------------------------------------------------------ ImageNet folder

class ImageFolderDataset:
    """ImageNet-layout folder (class-per-subdir); per-item JPEG decode +
    RandomResizedCrop/flip (train) or Resize+CenterCrop (eval).

    The reference's config 2/3 dataset (BASELINE.json:8-9). Item-style: the
    loader maps get_item over its thread pool (SURVEY C17 equivalent).
    """

    is_item_style = True

    def __init__(self, root: str, image_size: int, train: bool,
                 randaugment=None, raw_u8: bool = False):
        from PIL import Image  # noqa: F401  (verify import early)

        self.root = root
        self.image_size = image_size
        self.train = train
        self.randaugment = randaugment if train else None
        # raw_u8 (data.device_augment): decode + crop stay host-side
        # (RandomResizedCrop IS the decode-adjacent resample); flip,
        # RandAugment and normalize move into the jitted step, and the
        # item leaves as HWC uint8.
        self.raw_u8 = raw_u8
        classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        )
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples: list[tuple[str, int]] = []
        for c in classes:
            cdir = os.path.join(root, c)
            for f in sorted(os.listdir(cdir)):
                if f.lower().endswith((".jpg", ".jpeg", ".png")):
                    self.samples.append((os.path.join(cdir, f), self.class_to_idx[c]))

    def __len__(self) -> int:
        return len(self.samples)

    def _open_sample(self, i: int):
        """→ (PIL.Image, label). Overridden by the tar-shard variant."""
        from PIL import Image

        path, label = self.samples[i]
        return Image.open(path), label

    def get_item(self, i: int, rng: np.random.Generator) -> dict:
        from PIL import Image

        from pytorch_distributed_train_tpu.obs.perf import stage

        # Stage attribution (obs/perf.py): read = storage bytes → PIL
        # handle, decode = compressed bytes → pixels (convert forces the
        # lazy PIL load), augment = crop/flip/RandAugment/normalize.
        with stage("read"):
            pil, label = self._open_sample(i)
        with pil as im:
            with stage("decode"):
                im = im.convert("RGB")
            with stage("augment"):
                if self.train:
                    im = _random_resized_crop(im, self.image_size, rng)
                    if not self.raw_u8:
                        if rng.random() < 0.5:
                            im = im.transpose(Image.FLIP_LEFT_RIGHT)
                        if self.randaugment is not None:
                            im = self.randaugment(im, rng)
                else:
                    im = _center_crop(im, self.image_size)
                x_u8 = np.asarray(im, np.uint8)
        if self.raw_u8:
            # device-augment mode: flip/RandAugment/normalize happen in
            # the jitted step; the host ships uint8.
            return {"image": x_u8, "label": np.int32(label)}
        from pytorch_distributed_train_tpu.native import imgops

        with stage("augment"):
            if imgops.available():
                x = imgops.normalize_batch(
                    x_u8[None], IMAGENET_MEAN, IMAGENET_STD, nthreads=1)[0]
            else:
                x = (x_u8.astype(np.float32) / 255.0
                     - IMAGENET_MEAN) / IMAGENET_STD
        return {"image": x, "label": np.int32(label)}


def write_jpeg_tar_shard(path: str, n: int, rng, *, start_key: int = 0,
                         size_range: tuple[int, int] = (256, 513),
                         fixed_size: int | None = None,
                         num_classes: int = 1000, quality: int = 85,
                         per_image=None) -> None:
    """Synthesize ONE WebDataset-convention tar shard of photo-like JPEGs.

    The single writer for the ``<key>.jpg + <key>.cls`` layout that
    :class:`TarShardImageDataset` reads — bench.py's decode arm,
    tools/sustained_drill.py, and the pipeline/grain tests all call this,
    so the shard contract lives in exactly one place. "Photo-like" =
    low-res noise upsampled smooth: JPEG entropy (and decode cost) tracks
    real photos, where raw noise is the pathological worst case.
    ``per_image`` (optional) is called once per written image (progress /
    watchdog touch hooks). Writes directly to ``path`` — callers needing
    atomicity write to a temp name and rename.
    """
    import io
    import tarfile

    from PIL import Image

    with tarfile.open(path, "w") as tf:
        for k in range(n):
            if fixed_size is not None:
                W = H = fixed_size
            else:
                W = int(rng.integers(*size_range))
                H = int(rng.integers(*size_range))
            base = rng.integers(0, 256, (max(H // 8, 1), max(W // 8, 1), 3),
                                np.uint8)
            im = Image.fromarray(base).resize((W, H), Image.BILINEAR)
            buf = io.BytesIO()
            im.save(buf, "JPEG", quality=quality)
            data = buf.getvalue()
            info = tarfile.TarInfo(f"{start_key + k:06d}.jpg")
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
            cls = str(int(rng.integers(0, num_classes))).encode()
            info = tarfile.TarInfo(f"{start_key + k:06d}.cls")
            info.size = len(cls)
            tf.addfile(info, io.BytesIO(cls))
            if per_image is not None:
                per_image()


class TarShardImageDataset(ImageFolderDataset):
    """WebDataset-convention tar shards: each ``.tar`` holds ``<key>.jpg``
    (or .jpeg/.png) + ``<key>.cls`` (class index as ASCII) pairs. The
    ImageNet-at-scale storage layout — thousands of sequential-read shards
    instead of a million tiny files (object stores and network filesystems
    hate the latter). Same decode/augment path as ImageFolderDataset.

    Random access: member offsets are indexed once at startup (tar headers
    only); reads then seek directly into the shard. File handles are
    per-thread and dropped on pickle, so the dataset works under both the
    thread loader and Grain worker processes."""

    def __init__(self, pattern: str, image_size: int, train: bool,
                 randaugment=None, native_decode: bool = False,
                 decode_threads: int = 0, raw_u8: bool = False):
        import glob as glob_mod
        import tarfile

        self.image_size = image_size
        self.train = train
        self.randaugment = randaugment if train else None
        # raw_u8 (device augment) needs uint8 out, which the fused
        # native decode+normalize kernel cannot produce — the PIL
        # per-item path carries this mode (see ImageFolderDataset).
        self.raw_u8 = raw_u8
        if raw_u8:
            native_decode = False
        self.shards = sorted(glob_mod.glob(pattern))
        if not self.shards:
            raise FileNotFoundError(
                f"data.data_dir matched no .tar shards: {pattern!r}")
        # samples: (shard_idx, jpg_offset, jpg_size, label)
        self.samples = []  # type: ignore[assignment]
        has_non_jpeg = False
        for si, shard in enumerate(self.shards):
            pairs: dict[str, dict] = {}
            # mode "r:" = uncompressed only — autodetected gzip shards
            # would index offsets into the DECOMPRESSED stream that the
            # raw-seek read path can't honor; fail fast here instead of
            # handing gzip bytes to PIL later.
            with tarfile.open(shard, "r:") as tf:
                for m in tf:
                    if not m.isfile():
                        continue
                    key, dot, ext = m.name.rpartition(".")
                    ext = ext.lower()
                    entry = pairs.setdefault(key, {})
                    if ext in ("jpg", "jpeg", "png"):
                        entry["img"] = (m.offset_data, m.size)
                        has_non_jpeg |= ext == "png"
                    elif ext == "cls":
                        f = tf.extractfile(m)
                        entry["label"] = int(f.read().strip())  # type: ignore[union-attr]
            for key in sorted(pairs):
                entry = pairs[key]
                if "img" in entry and "label" in entry:
                    off, size = entry["img"]
                    self.samples.append((si, off, size, entry["label"]))
        if not self.samples:
            raise ValueError(
                f"tar shards {self.shards} contain no (img, cls) pairs")
        # Native decode path (SURVEY §7.4.1): libjpeg batch decode + crop
        # resize + normalize in C++ threads instead of per-item PIL. Only
        # when every image is JPEG, RandAugment is off (PIL-op chain), and
        # the library builds — silently fall back otherwise: the knob is a
        # throughput choice, not a semantics one.
        self.native_decode = False
        self.decode_threads = decode_threads  # 0 → jpegdec.default_threads
        self._decode_failures = 0
        self._failure_warnings = 0
        if native_decode and not has_non_jpeg and self.randaugment is None:
            from pytorch_distributed_train_tpu.native import jpegdec

            self.native_decode = jpegdec.available()
        if self.native_decode:
            self.is_item_style = False  # loader calls get_batch instead
        import threading

        self._local = threading.local()

    def __getstate__(self):
        d = self.__dict__.copy()
        d.pop("_local", None)  # open handles never cross process forks
        return d

    def __setstate__(self, d):
        import threading

        self.__dict__.update(d)
        self._local = threading.local()

    _MAX_OPEN_PER_THREAD = 64

    def _handle(self, si: int):
        # LRU-bounded per-thread handle cache: random access touches every
        # shard eventually, and thousands-of-shards x N threads of open
        # fds would blow typical ulimits mid-epoch.
        files = getattr(self._local, "files", None)
        if files is None:
            files = self._local.files = {}
        fh = files.pop(si, None)
        if fh is None:
            if len(files) >= self._MAX_OPEN_PER_THREAD:
                oldest = next(iter(files))  # dict order = LRU order
                files.pop(oldest).close()
            fh = open(self.shards[si], "rb")
        files[si] = fh  # reinsert → most-recently-used position
        return fh

    def _open_sample(self, i: int):
        import io

        from PIL import Image

        si, off, size, label = self.samples[i]
        fh = self._handle(si)
        fh.seek(off)
        return Image.open(io.BytesIO(fh.read(size))), label

    def get_batch(self, idx, rng: np.random.Generator, train: bool) -> dict:
        """Native decode path: raw bytes out of the shard (Python, cheap) →
        one jpegdec call (C++ threads, no GIL) doing decode + crop-box
        bilinear resize + flip + normalize. Boxes come from the SAME
        _rrc_box/_center_box policy the PIL path uses; only the resampler
        differs (plain bilinear vs PIL's filtered resize — documented in
        native/jpegdec.cpp). Corrupt members decode to zeros rather than
        poisoning the epoch."""
        from pytorch_distributed_train_tpu.native import jpegdec
        from pytorch_distributed_train_tpu.obs.perf import stage

        blobs: list[bytes] = []
        labels = np.empty(len(idx), np.int32)
        with stage("read"):
            for n, i in enumerate(idx):
                si, off, size, label = self.samples[int(i)]
                fh = self._handle(si)
                fh.seek(off)
                blobs.append(fh.read(size))
                labels[n] = label
        dims = jpegdec.dims(blobs)
        B = len(blobs)
        boxes = np.empty((B, 4), np.float32)
        flips = np.zeros(B, bool)
        for n in range(B):
            W, H = int(dims[n, 0]), int(dims[n, 1])
            if W == 0 or H == 0:
                boxes[n] = (0.0, 0.0, 1.0, 1.0)  # corrupt: zeroed below
                continue
            if train:
                box = _rrc_box(W, H, rng)
                boxes[n] = box if box is not None else _center_box(W, H)
                flips[n] = rng.random() < 0.5
            else:
                boxes[n] = _center_box(W, H)
        # The fused native pass does decode + crop-resize + normalize in
        # one C++ call; it is attributed to `decode` whole (decode
        # dominates, and the fusion is the point — splitting it would
        # mean un-fusing the kernel to measure it).
        with stage("decode"):
            images, fails = jpegdec.decode_batch(
                blobs, boxes, flips, self.image_size,
                IMAGENET_MEAN, IMAGENET_STD, nthreads=self.decode_threads)
        if fails:
            # Zero-filled images keep real labels — survivable (one bad
            # sample must not kill an epoch) but must be LOUD: systematic
            # corruption silently degrading accuracy is the failure mode.
            self._decode_failures += fails
            if self._failure_warnings < 5:
                self._failure_warnings += 1
                import sys

                print(
                    f"[jpegdec] {fails} corrupt image(s) in batch "
                    f"(total {self._decode_failures} this dataset) — "
                    "zero-filled"
                    + ("; suppressing further warnings"
                       if self._failure_warnings == 5 else ""),
                    file=sys.stderr, flush=True)
        return {"image": images, "label": labels}


def _rrc_box(W: int, H: int, rng: np.random.Generator):
    """RandomResizedCrop box (x0, y0, w, h) in source coords, or None after
    10 failed attempts (caller falls back to center crop). Pure function of
    (dims, rng) so the PIL and native-decode paths draw identical boxes."""
    area = W * H
    for _ in range(10):
        target = area * rng.uniform(0.08, 1.0)
        ratio = np.exp(rng.uniform(np.log(3 / 4), np.log(4 / 3)))
        w = int(round(np.sqrt(target * ratio)))
        h = int(round(np.sqrt(target / ratio)))
        if 0 < w <= W and 0 < h <= H:
            x0 = int(rng.integers(0, W - w + 1))
            y0 = int(rng.integers(0, H - h + 1))
            return (x0, y0, w, h)
    return None


def _center_box(W: int, H: int):
    """Center-crop box equivalent of _center_crop's resize-then-crop: a
    centered square of side min(W,H)·224/256, resized to target by the
    caller. (Sub-pixel rounding differs from the PIL path's two-step
    resize; visually and statistically identical.)"""
    side = min(W, H) * 224.0 / 256.0
    return ((W - side) / 2.0, (H - side) / 2.0, side, side)


def _random_resized_crop(im, size: int, rng: np.random.Generator):
    from PIL import Image

    W, H = im.size
    box = _rrc_box(W, H, rng)
    if box is None:
        return _center_crop(im, size)
    x0, y0, w, h = box
    return im.resize((size, size), Image.BILINEAR, box=(x0, y0, x0 + w, y0 + h))


def _center_crop(im, size: int):
    from PIL import Image

    W, H = im.size
    scale = size / min(W, H) * 256 / 224  # resize shorter side to size*256/224
    im = im.resize((max(1, int(W * scale)), max(1, int(H * scale))), Image.BILINEAR)
    W, H = im.size
    x0, y0 = (W - size) // 2, (H - size) // 2
    return im.crop((x0, y0, x0 + size, y0 + size))


# ------------------------------------------------------------------ factory

def _build_randaugment(data_cfg, train: bool):
    if not train or data_cfg.randaugment_num_ops <= 0:
        return None
    # With device augment on, the RandAugment op space runs on-device
    # inside the jitted step (ops/device_augment.py) — a host-side PIL
    # chain here would double-augment.
    if getattr(data_cfg, "device_augment", False):
        return None
    from pytorch_distributed_train_tpu.data.augment import RandAugment

    return RandAugment(data_cfg.randaugment_num_ops,
                       data_cfg.randaugment_magnitude)


def _want_raw_u8(data_cfg) -> bool:
    return bool(getattr(data_cfg, "device_augment", False))


def _packed_or_none(data_cfg, train: bool):
    """data.packed_cache_dir: a valid packed cache for the split
    replaces the decode path (data/packed_cache.py — hit/miss counted
    in the registry); anything else falls through to the original
    dataset build."""
    cache_dir = getattr(data_cfg, "packed_cache_dir", "")
    if not cache_dir:
        return None
    from pytorch_distributed_train_tpu.data.packed_cache import (
        load_packed_if_present,
    )

    return load_packed_if_present(
        cache_dir, "train" if train else "val", augment=train,
        randaugment=_build_randaugment(data_cfg, train),
        verify=getattr(data_cfg, "packed_verify", False),
        raw_u8=_want_raw_u8(data_cfg))


def build_dataset(data_cfg, model_cfg, train: bool):
    name = data_cfg.dataset
    if name in ("cifar10", "imagenet_folder", "imagenet_tar"):
        packed = _packed_or_none(data_cfg, train)
        if packed is not None:
            return packed
    if name == "packed_images":
        # Direct packed-shard dataset: data_dir is a shard directory,
        # glob, or single file (tools/pack_dataset.py output).
        from pytorch_distributed_train_tpu.data.packed_cache import (
            PackedImageDataset,
        )

        return PackedImageDataset(
            data_cfg.data_dir, augment=train,
            randaugment=_build_randaugment(data_cfg, train),
            verify=getattr(data_cfg, "packed_verify", False),
            raw_u8=_want_raw_u8(data_cfg),
            split="train" if train else "val")
    if name == "cifar10":
        ds = load_cifar10(data_cfg.data_dir, train,
                          randaugment=_build_randaugment(data_cfg, train))
        if _want_raw_u8(data_cfg) and isinstance(ds, U8ImageDataset):
            ds.raw_u8 = True
        return ds
    if name == "synthetic_images":
        return synthetic_images(
            data_cfg.synthetic_size, model_cfg.image_size, model_cfg.num_classes,
            seed=0 if train else 1,
        )
    if name == "imagenet_folder":
        split = "train" if train else "val"
        root = os.path.join(data_cfg.data_dir, split)
        if not os.path.isdir(root):
            return synthetic_images(
                data_cfg.synthetic_size, model_cfg.image_size,
                model_cfg.num_classes, seed=0 if train else 1,
            )
        return ImageFolderDataset(root, model_cfg.image_size, train,
                                  randaugment=_build_randaugment(data_cfg, train),
                                  raw_u8=_want_raw_u8(data_cfg))
    if name == "imagenet_tar":
        # WebDataset-style shards: data_dir is a glob per split, e.g.
        # '/data/imagenet-{split}-*.tar' ({split} → train|val), or a
        # plain glob used for both splits.
        pattern = data_cfg.data_dir.replace(
            "{split}", "train" if train else "val")
        return TarShardImageDataset(
            pattern, model_cfg.image_size, train,
            randaugment=_build_randaugment(data_cfg, train),
            native_decode=data_cfg.native_decode,
            decode_threads=data_cfg.num_workers,
            raw_u8=_want_raw_u8(data_cfg))
    if name == "synthetic_lm":
        return synthetic_lm(
            data_cfg.synthetic_size, data_cfg.seq_len, model_cfg.vocab_size,
            seed=0 if train else 1,
        )
    if name == "synthetic_dpo":
        return synthetic_dpo(
            data_cfg.synthetic_size, data_cfg.seq_len,
            model_cfg.vocab_size, seed=0 if train else 1,
        )
    if name == "synthetic_seq2seq":
        return synthetic_seq2seq(
            data_cfg.synthetic_size, data_cfg.seq_len,
            data_cfg.tgt_seq_len or data_cfg.seq_len,
            model_cfg.vocab_size, seed=0 if train else 1,
        )
    if name == "text_lm":
        from pytorch_distributed_train_tpu.data.text import build_text_dataset

        return build_text_dataset(data_cfg, model_cfg, train, mlm=False)
    if name == "text_mlm":
        if data_cfg.text_files:
            from pytorch_distributed_train_tpu.data.text import (
                build_text_dataset,
            )

            return build_text_dataset(data_cfg, model_cfg, train, mlm=True)
        return synthetic_mlm(
            data_cfg.synthetic_size, data_cfg.seq_len, model_cfg.vocab_size,
            data_cfg.mlm_prob, seed=0 if train else 1,
        )
    raise KeyError(f"unknown dataset {name!r}")
