"""Distributed index sampler — exact torch DistributedSampler semantics.

Behavioral spec (torch:utils/data/distributed.py:107-146, SURVEY C16):
- epoch-seeded permutation: `g.manual_seed(seed + epoch)` then randperm
  (:110-113) — reshuffles every epoch via `set_epoch`, identically on every
  rank with no communication;
- pad to divisible: indices are repeated from the front until
  len % num_replicas == 0 (:117-126) when drop_last=False, else truncated;
- stride subsample: rank takes indices[rank::num_replicas] (:134).

Property (tested): the union of all ranks' shards is exactly the padded
permutation; every rank's shard has identical length (SPMD static shapes).

Here "rank" is the HOST (jax process), not the chip: each host loads the
shard for all its local devices and the global jax.Array assembles the rest.
"""

from __future__ import annotations

import numpy as np


class DistributedSampler:
    def __init__(
        self,
        dataset_len: int,
        num_replicas: int,
        rank: int,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if rank >= num_replicas or rank < 0:
            raise ValueError(f"rank {rank} out of range for {num_replicas} replicas")
        self.dataset_len = dataset_len
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0

        if drop_last and dataset_len % num_replicas != 0:
            self.num_samples = dataset_len // num_replicas
        else:
            self.num_samples = (dataset_len + num_replicas - 1) // num_replicas
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch: int) -> None:
        """Per-epoch reshuffle hook — same contract as
        torch:utils/data/distributed.py:146."""
        self.epoch = epoch

    def indices(self) -> np.ndarray:
        if self.shuffle:
            g = np.random.default_rng(self.seed + self.epoch)
            idx = g.permutation(self.dataset_len)
        else:
            idx = np.arange(self.dataset_len)

        if not self.drop_last:
            pad = self.total_size - len(idx)
            if pad > 0:
                # repeat from the front (wrap) — torch's behavior :120-126
                reps = int(np.ceil(pad / len(idx)))
                idx = np.concatenate([idx, np.tile(idx, reps)[:pad]])
        else:
            idx = idx[: self.total_size]

        assert len(idx) == self.total_size
        return idx[self.rank :: self.num_replicas]

    def __iter__(self):
        return iter(self.indices())

    def __len__(self) -> int:
        return self.num_samples
