"""Distributed index sampler — exact torch DistributedSampler semantics.

Behavioral spec (torch:utils/data/distributed.py:107-146, SURVEY C16):
- epoch-seeded permutation: `g.manual_seed(seed + epoch)` then randperm
  (:110-113) — reshuffles every epoch via `set_epoch`, identically on every
  rank with no communication;
- pad to divisible: indices are repeated from the front until
  len % num_replicas == 0 (:117-126) when drop_last=False, else truncated;
- stride subsample: rank takes indices[rank::num_replicas] (:134).

Property (tested): the union of all ranks' shards is exactly the padded
permutation; every rank's shard has identical length (SPMD static shapes).

Here "rank" is the HOST (jax process), not the chip: each host loads the
shard for all its local devices and the global jax.Array assembles the rest.
"""

from __future__ import annotations

import numpy as np


class DistributedSampler:
    def __init__(
        self,
        dataset_len: int,
        num_replicas: int,
        rank: int,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if rank >= num_replicas or rank < 0:
            raise ValueError(f"rank {rank} out of range for {num_replicas} replicas")
        self.dataset_len = dataset_len
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0

        if drop_last and dataset_len % num_replicas != 0:
            self.num_samples = dataset_len // num_replicas
        else:
            self.num_samples = (dataset_len + num_replicas - 1) // num_replicas
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch: int) -> None:
        """Per-epoch reshuffle hook — same contract as
        torch:utils/data/distributed.py:146."""
        self.epoch = epoch

    def indices(self) -> np.ndarray:
        if self.shuffle:
            g = np.random.default_rng(self.seed + self.epoch)
            idx = g.permutation(self.dataset_len)
        else:
            idx = np.arange(self.dataset_len)

        if not self.drop_last:
            pad = self.total_size - len(idx)
            if pad > 0:
                # repeat from the front (wrap) — torch's behavior :120-126
                reps = int(np.ceil(pad / len(idx)))
                idx = np.concatenate([idx, np.tile(idx, reps)[:pad]])
        else:
            idx = idx[: self.total_size]

        assert len(idx) == self.total_size
        return idx[self.rank :: self.num_replicas]

    def __iter__(self):
        return iter(self.indices())

    def __len__(self) -> int:
        return self.num_samples


class WeightedDistributedSampler(DistributedSampler):
    """torch WeightedRandomSampler semantics, made distributed-aware.

    torch's WeightedRandomSampler (torch recipe for class-imbalanced data)
    draws ``num_samples`` indices WITH replacement, proportionally to a
    per-sample weight vector; in DDP recipes it is wrapped per-rank. Here
    the weighted draw replaces the permutation directly: identical on every
    host (seed+epoch rng, no communication), padded/stride-sharded like the
    base class, reshuffled per epoch.
    """

    def __init__(self, weights: np.ndarray, num_replicas: int, rank: int,
                 seed: int = 0, drop_last: bool = False,
                 num_samples: int | None = None):
        total = num_samples if num_samples is not None else len(weights)
        super().__init__(total, num_replicas, rank, shuffle=True, seed=seed,
                         drop_last=drop_last)
        weights = np.asarray(weights, np.float64)
        if (weights < 0).any() or weights.sum() <= 0:
            raise ValueError("weights must be non-negative with positive sum")
        self.weights = weights / weights.sum()

    def indices(self) -> np.ndarray:
        g = np.random.default_rng(self.seed + self.epoch)
        idx = g.choice(len(self.weights), size=self.total_size, replace=True,
                       p=self.weights)
        return idx[self.rank :: self.num_replicas]


def inverse_class_weights(labels: np.ndarray) -> np.ndarray:
    """Per-sample weights ∝ 1/class-frequency — the standard torch
    WeightedRandomSampler recipe for imbalanced classification."""
    labels = np.asarray(labels)
    _, inverse, counts = np.unique(labels, return_inverse=True,
                                   return_counts=True)
    return (1.0 / counts)[inverse]


def make_weighted_sampler(dataset, data_cfg, num_hosts: int, host_id: int):
    """Shared factory for the ``weighted_sampling`` knob — the 'threads'
    and 'grain' loaders must construct (and reject) identically, or the
    train distribution silently depends on the loader choice."""
    scheme = getattr(data_cfg, "weighted_sampling", "")
    if scheme != "inverse_class":
        raise ValueError(
            f"weighted_sampling must be '' or 'inverse_class', "
            f"got {scheme!r}")
    labels = getattr(dataset, "arrays", {}).get("label")
    if labels is None:
        raise ValueError(
            "weighted_sampling='inverse_class' needs an array-style "
            "dataset with a 'label' array")
    return WeightedDistributedSampler(
        inverse_class_weights(labels), num_hosts, host_id,
        seed=data_cfg.seed,
    )
