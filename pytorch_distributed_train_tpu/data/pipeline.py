"""Host data loader + HBM prefetch (SURVEY C17, §3.4 TPU mapping).

Pipeline stages, each overlapped with the next:

  sampler indices ─→ [worker threads: decode/augment/collate]
                 ─→ [background producer thread, bounded queue]
                 ─→ [jax.make_array_from_process_local_data → HBM,
                     `prefetch`-deep buffer]  ─→ jitted step

Threads replace the reference's DataLoader worker *processes*
(torch:utils/data/_utils/worker.py:244): PIL decode and numpy release the
GIL, and there is no CUDA pinned-memory dance — device_put DMAs straight to
HBM while the previous step runs (the double-buffer the reference gets from
its pin-memory thread + non_blocking copies, torch:utils/data/_utils/
pin_memory.py:18).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from pytorch_distributed_train_tpu.data.sampler import DistributedSampler
from pytorch_distributed_train_tpu.obs import perf as perf_lib
from pytorch_distributed_train_tpu.obs.spans import span as _span


class StallStats:
    """Input-stall accounting: cumulative time the CONSUMER blocked waiting
    for the host pipeline to produce a batch.

    The feed-ratio question (SURVEY §7.4.1 — the #1-ranked hard part) is
    whether the host can keep the chip fed; sustained-run acceptance is
    "input_stall_pct < 5" (BASELINE.json:8 drill). The counter sits at the
    producer-queue get: with async device_put downstream, that wait IS the
    time the step loop would have idled on input. Plain float adds under
    the GIL — one writer (the consumer thread) — no lock needed.

    Each add also mirrors into the scrape registry
    (``input_stall_seconds_total{split=...}``) so a live /metrics poll
    sees the stall trend without waiting for the next JSONL window.
    """

    def __init__(self, split: str = "train") -> None:
        self.waits = 0
        self.wait_s = 0.0
        from pytorch_distributed_train_tpu.obs.registry import get_registry

        self._counter = get_registry().counter(
            "input_stall_seconds_total", labels={"split": split},
            help="cumulative seconds the consumer blocked on the host "
                 "input pipeline")

    def add(self, dt: float) -> None:
        self.waits += 1
        self.wait_s += dt
        self._counter.inc(dt)


# Per-process thread pool for item-style collate INSIDE shared-memory
# decode workers (data/workers.py): a module global rebuilt lazily per
# process (pid-guarded — executor threads never survive a fork). The
# in-process path keeps the loader-owned pool (self._pool) unchanged.
_ITEM_POOL: tuple[int, ThreadPoolExecutor] | None = None


def _item_pool(num_workers: int) -> ThreadPoolExecutor:
    global _ITEM_POOL
    if _ITEM_POOL is None or _ITEM_POOL[0] != os.getpid():
        from pytorch_distributed_train_tpu.data import workers as workers_lib

        # python_thread_budget (no x2): PIL item decode holds the GIL
        # through its Python framing — inside a forked mp worker the
        # pool clamps to exactly the worker's core share (the LKG
        # pil_grain_mp8 oversubscription fix, ISSUE 14 satellite).
        _ITEM_POOL = (os.getpid(), ThreadPoolExecutor(
            max_workers=workers_lib.python_thread_budget(num_workers)))
    return _ITEM_POOL[1]


def collate_chunk(dataset, chunk: np.ndarray, *, seed: int, epoch: int,
                  batch_index: int, host_id: int, train: bool,
                  pool=None, num_workers: int = 4) -> dict:
    """Collate ONE host batch — the single definition of the threads
    loader's batch semantics, shared byte-exactly by the in-process path
    (HostDataLoader._collate) and the shared-memory decode workers.

    The per-batch rng is keyed on (seed, epoch, batch-index, host), so
    batch b is identical wherever (and in whichever process) it is
    materialized — the invariant every resume/elastic test pins.
    `data.decode` fault point + retry/backoff (faults/): transient decode
    errors back off and retry; a record that stays undecodable is
    substituted-and-counted — static SPMD shapes forbid dropping a row.
    """
    from pytorch_distributed_train_tpu import faults as faults_lib

    rng = np.random.default_rng(
        np.random.SeedSequence((seed, epoch, batch_index, host_id)))
    if not getattr(dataset, "is_item_style", False):
        def _load_batch(_i=None):
            faults_lib.maybe_fire("data.decode")
            return dataset.get_batch(chunk, rng, train)

        return faults_lib.retry_call(_load_batch, point="data.decode")
    seeds = rng.integers(0, 2**63, size=len(chunk))
    n = len(dataset)

    def _load_one(a):
        i, item_seed = int(a[0]), int(a[1])

        def load(j):
            faults_lib.maybe_fire("data.decode")
            return dataset.get_item(j, np.random.default_rng(item_seed))

        return faults_lib.decode_with_retry(load, i, n)

    if pool is None:
        pool = _item_pool(num_workers)
    items = list(pool.map(_load_one, zip(chunk, seeds)))
    return {k: np.stack([it[k] for it in items]) for k in items[0]}


class HostDataLoader:
    """Per-host loader: yields this host's shard of each global batch.

    Length semantics: drop_last=True (training) truncates to full batches —
    required for SPMD static shapes (SURVEY §7.4.5); eval pads the tail batch
    by wrapping (sampler already padded to host-divisibility).

    With ``data.mp_workers > 0`` the collate runs in the shared-memory
    decode pool (data/workers.py) instead of this process — same batch
    bytes, same resume semantics, N processes of decode/augment.
    """

    def __init__(self, dataset, data_cfg, *, train: bool,
                 num_hosts: int | None = None, host_id: int | None = None):
        self.dataset = dataset
        self.train = train
        self.num_hosts = num_hosts if num_hosts is not None else jax.process_count()
        self.host_id = host_id if host_id is not None else jax.process_index()
        global_batch = data_cfg.batch_size if train else (
            data_cfg.eval_batch_size or data_cfg.batch_size
        )
        if global_batch % self.num_hosts != 0:
            raise ValueError(
                f"global batch {global_batch} not divisible by {self.num_hosts} hosts"
            )
        self.host_batch = global_batch // self.num_hosts
        self.global_batch = global_batch
        self.seed = data_cfg.seed
        self.num_workers = data_cfg.num_workers
        if train and getattr(data_cfg, "weighted_sampling", ""):
            from pytorch_distributed_train_tpu.data.sampler import (
                make_weighted_sampler,
            )

            self.sampler = make_weighted_sampler(
                dataset, data_cfg, self.num_hosts, self.host_id)
        else:
            self.sampler = DistributedSampler(
                len(dataset), self.num_hosts, self.host_id,
                shuffle=train and data_cfg.shuffle, seed=data_cfg.seed,
                drop_last=False,
            )
        self._pool: ThreadPoolExecutor | None = None
        self._owner_pid = os.getpid()
        # Shared-memory decode pool (data/workers.py) — built lazily on
        # the first epoch so tests/tools constructing loaders never fork.
        from pytorch_distributed_train_tpu.data import workers as workers_lib

        self.mp_workers = (
            workers_lib.pool_budget(getattr(data_cfg, "mp_workers", 0))
            if workers_lib.available() else 0)
        self.mp_slots = getattr(data_cfg, "mp_slots", 0)
        self._mp_pool = None

    @property
    def steps_per_epoch(self) -> int:
        n = self.sampler.num_samples
        if self.train:
            return n // self.host_batch
        return (n + self.host_batch - 1) // self.host_batch

    def close(self) -> None:
        """Release the shared-memory pool (bench/tests; the trainer's
        daemonic workers die with the process either way)."""
        if self._mp_pool is not None:
            self._mp_pool.close()
            self._mp_pool = None

    def _epoch_chunks(self, epoch: int) -> np.ndarray:
        self.sampler.set_epoch(epoch)
        idx = self.sampler.indices()
        n_steps = self.steps_per_epoch
        if not self.train:
            # pad tail by wrapping so every step is full-size (weights unused
            # rows are the caller's concern only for exact eval metrics).
            # np.resize tiles cyclically — datasets smaller than one batch
            # (tiny eval holdouts) still fill a whole batch, where a single
            # wrap-around concat would come up short and break the sharded
            # device_put's divisibility contract.
            need = n_steps * self.host_batch
            if len(idx) < need:
                idx = np.resize(idx, need)
        return idx

    def epoch(self, epoch: int, start_batch: int = 0) -> Iterator[dict]:
        """Yield host-local numpy batches for one epoch.

        ``start_batch`` fast-forwards a mid-epoch resume: the per-batch rng
        is seeded by (seed, epoch, batch-index, host), so batch b is
        identical whether or not batches before it were materialized — the
        resumed stream continues exactly where the crashed run stopped
        (stronger than the reference, which replays the epoch). Batch
        composition is also invariant to ``mp_workers``: the pool receives
        the SAME (batch-index, chunk) tasks this loop would collate."""
        idx = self._epoch_chunks(epoch)
        n_steps = self.steps_per_epoch
        tasks = ((epoch, b, idx[b * self.host_batch:(b + 1) * self.host_batch])
                 for b in range(start_batch, n_steps))
        if self.mp_workers > 0:
            if self._mp_pool is None:
                from pytorch_distributed_train_tpu.data import (
                    workers as workers_lib,
                )

                self._mp_pool = workers_lib.SharedMemoryWorkerPool(
                    self._pool_collate, self.mp_workers,
                    slots=self.mp_slots,
                    post_fork=lambda: workers_lib.reset_thread_local_state(
                        self.dataset))
            return self._mp_pool.run(tasks)
        return (self._pool_collate(t) for t in tasks)

    def _pool_collate(self, task) -> dict:
        """One (epoch, batch-index, chunk) task → batch dict. Runs on
        the consumer thread OR inside a forked decode worker — both call
        the same collate_chunk, so the bytes cannot diverge. The loader-
        owned item thread pool is only usable in the process that built
        it (executor threads never survive a fork); elsewhere
        collate_chunk falls back to the per-process module pool."""
        epoch, b, chunk = task
        pool = None
        if getattr(self.dataset, "is_item_style", False) \
                and os.getpid() == self._owner_pid:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=max(1, self.num_workers))
            pool = self._pool
        return collate_chunk(
            self.dataset, chunk, seed=self.seed, epoch=epoch,
            batch_index=b, host_id=self.host_id, train=self.train,
            pool=pool, num_workers=self.num_workers)


class _Producer(threading.Thread):
    """Background producer draining an iterator into a bounded queue —
    keeps host-side collate off the step critical path.

    Shut-down safe: an abandoned consumer (early break from the epoch, step
    cap reached) calls stop() from the iterator's finally, which unblocks a
    producer wedged on a full queue — no leaked threads holding prefetch
    buffers."""

    _DONE = object()

    def __init__(self, it: Iterator, depth: int,
                 stats: StallStats | None = None):
        super().__init__(daemon=True)
        self.it = it
        self.q: queue.Queue = queue.Queue(maxsize=depth)
        self.error: BaseException | None = None
        self.stats = stats
        self._stopped = threading.Event()
        from pytorch_distributed_train_tpu.obs.registry import get_registry

        # Prefetch-occupancy gauge (obs/perf.py plane): queue fill
        # fraction sampled at every consumer get — 0.0 sustained means
        # the producer never gets ahead (input-bound), 1.0 means the
        # chip is the bottleneck. The scrapable twin of input_stall_pct.
        self._occupancy = get_registry().gauge(
            "input_prefetch_occupancy",
            help="producer->consumer prefetch queue fill fraction at "
                 "consumer gets (0 = input-bound, 1 = chip-bound)")
        self.start()

    _EXHAUSTED = object()

    def run(self):
        try:
            it = iter(self.it)
            while True:
                # span per produced batch: the trace shows host collate
                # time interleaved with the consumer's step spans (the
                # two-thread overlap the pipeline exists to create).
                # next(it, sentinel), not try/except StopIteration — a
                # StopIteration raised through the span contextmanager
                # generator would become a PEP 479 RuntimeError.
                with _span("data.produce"):
                    item = next(it, self._EXHAUSTED)
                if item is self._EXHAUSTED:
                    break
                while not self._stopped.is_set():
                    try:
                        self.q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stopped.is_set():
                    return
        except BaseException as e:  # surfaced on the consumer side
            self.error = e
        finally:
            # blocking-with-stop-check put: the queue may be full here, and
            # dropping the marker would wedge the consumer on q.get() forever
            while not self._stopped.is_set():
                try:
                    self.q.put(self._DONE, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def stop(self) -> None:
        self._stopped.set()
        try:
            while True:
                self.q.get_nowait()
        except queue.Empty:
            pass

    def __iter__(self):
        try:
            while True:
                self._occupancy.set(
                    self.q.qsize() / max(self.q.maxsize, 1))
                t0 = time.perf_counter()
                item = self.q.get()
                if self.stats is not None:
                    # Non-empty-queue gets cost microseconds; genuine
                    # stalls dominate the sum, so unconditional adds keep
                    # the hot path branch-free and the number honest.
                    self.stats.add(time.perf_counter() - t0)
                if item is self._DONE:
                    if self.error is not None:
                        raise self.error
                    return
                yield item
        finally:
            self.stop()


def device_prefetch(host_batches: Iterator[dict], mesh, batch_axes=("data", "fsdp"),
                    depth: int = 2) -> Iterator[dict]:
    """Assemble global jax.Arrays from host-local shards and keep `depth`
    batches in flight to HBM (BASELINE.json:5 'device-side prefetch to HBM').

    device_put is async — enqueueing the transfer returns immediately, so the
    DMA for batch N+1 overlaps step N's compute.
    """
    sharding = NamedSharding(mesh, PartitionSpec(tuple(batch_axes)))

    def to_device(b: dict) -> dict:
        # h2d stage (obs/perf.py): global-array assembly + the transfer
        # enqueue. device_put is async, so this times dispatch, not the
        # DMA itself — a SYNCHRONOUS h2d bottleneck (transfer backlog
        # applying back-pressure here) still shows up as this stage
        # dominating the split.
        with perf_lib.stage("h2d"):
            return {
                k: jax.make_array_from_process_local_data(sharding, v)
                for k, v in b.items()
            }

    buf: deque = deque()
    try:
        for b in host_batches:
            buf.append(to_device(b))
            if len(buf) >= depth:
                yield buf.popleft()
        while buf:
            yield buf.popleft()
    finally:
        close = getattr(host_batches, "close", None)
        if close is not None:
            close()


def build_input_pipeline(dataset, data_cfg, mesh, *, train: bool,
                         batch_axes=("data", "fsdp"), sync_check_every: int = 0,
                         num_hosts: int | None = None,
                         host_id: int | None = None):
    """Convenience: loader + producer thread + device prefetch.

    Returns (loader, epoch_fn) where epoch_fn(epoch) yields device-resident
    global batches. ``sync_check_every`` enables the cross-host input
    divergence check (SURVEY §5.2) on HOST-LOCAL batches, before global
    array assembly — after assembly all hosts see identical global shapes by
    construction, so checking there would be vacuous. The check runs on the
    consumer thread (collectives must not race the step's collectives).
    ``num_hosts``/``host_id`` override the jax process world for the
    loader's sharding — the elastic-reshard path (``data.elastic_shards``)
    passes the LAUNCHER world here, recomputed per restart generation.
    """
    if getattr(data_cfg, "loader", "threads") == "grain":
        from pytorch_distributed_train_tpu.data.grain_pipeline import (
            GrainHostDataLoader,
        )

        loader = GrainHostDataLoader(dataset, data_cfg, train=train,
                                     num_hosts=num_hosts, host_id=host_id)
    else:
        loader = HostDataLoader(dataset, data_cfg, train=train,
                                num_hosts=num_hosts, host_id=host_id)
    # read by the trainer's log window; mirrored to /metrics by split
    loader.stall_stats = StallStats(split="train" if train else "eval")

    def epoch_fn(epoch: int, start_batch: int = 0) -> Iterator[dict]:
        host_iter = iter(_Producer(loader.epoch(epoch, start_batch),
                                   depth=max(2, data_cfg.prefetch),
                                   stats=loader.stall_stats))
        if sync_check_every:
            from pytorch_distributed_train_tpu.utils.debug import check_input_sync

            def checked(it):
                for i, b in enumerate(it):
                    if i % sync_check_every == 0:
                        check_input_sync(b)
                    yield b

            host_iter = checked(host_iter)
        return device_prefetch(
            host_iter, mesh, batch_axes=batch_axes, depth=data_cfg.prefetch
        )

    return loader, epoch_fn
