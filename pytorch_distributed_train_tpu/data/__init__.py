"""Per-host sharded input pipeline (SURVEY L3, §3.4).

Replaces the reference's DataLoader machinery (torch:utils/data/dataloader.py:149,
worker processes, pin-memory thread) and DistributedSampler
(torch:utils/data/distributed.py:17) with: an index sampler reproducing the
exact seed+epoch shuffle / pad / stride semantics, per-host dataset shards,
a threaded prefetch loader, and device-put double-buffering into HBM so step
N+1's batch lands while step N computes (BASELINE.json:5 "device-side
prefetch to HBM").
"""

from pytorch_distributed_train_tpu.data.sampler import DistributedSampler  # noqa: F401
from pytorch_distributed_train_tpu.data.pipeline import build_input_pipeline  # noqa: F401
