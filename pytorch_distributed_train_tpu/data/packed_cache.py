"""Packed pre-decoded sample cache (ROADMAP item 2, ISSUE 12b).

PR 9's staged attribution pinned the native tar-decode preset at
79% augment / 21% read — but the *decode* presets spend their wall
re-running libjpeg on bytes that never change between epochs. This
module trades disk for that work: an on-disk FIXED-RECORD uint8 format,
built once by ``tools/pack_dataset.py``, that turns the read+decode
stages into a single mmap'd strided read. One record = one pre-decoded
HxWxC uint8 image + its label; fixed records mean record *i* lives at a
computable offset, so a shuffled epoch is pure ``memmap[idx]`` fancy
indexing — the kernel's page cache does the rest.

Shard layout (little-endian)::

    magic   8 bytes   b"PDTTPCK1"
    hlen    4 bytes   uint32, length of the JSON header
    header  hlen      JSON: {n, shape, image_dtype, label_dtype,
                             crc32, meta{mean, std, ...}}
    images  n*H*W*C   uint8, C-contiguous (n, H, W, C)
    labels  n*4       int32

``crc32`` covers the payload (images+labels bytes) — corruption is
detectable per shard (``verify_shard``), and the pack tool verifies
what it wrote before declaring success. Readers mmap the images region
and load labels to RAM (4 bytes/record).

Registry metrics: cache hit/miss at dataset build
(``packed_cache_{hits,misses}_total``), records served
(``packed_cache_records_read_total``), CRC failures
(``packed_cache_crc_failures_total``), and build-side counters from the
pack tool (``packed_cache_build_records_total`` /
``packed_cache_build_seconds``).

The reader dataset (:class:`PackedImageDataset`) subclasses
U8ImageDataset, so the augment/normalize path (native imgops pass,
RandAugment, device-augment raw-u8 mode) is byte-identical to the
in-RAM eager path — the identity the tier-1 tests pin.
"""

from __future__ import annotations

import glob as glob_mod
import json
import os
import struct
import zlib

import numpy as np

from pytorch_distributed_train_tpu.data.datasets import (
    IMAGENET_MEAN,
    IMAGENET_STD,
    U8ImageDataset,
)
from pytorch_distributed_train_tpu.obs.registry import get_registry

MAGIC = b"PDTTPCK1"
SHARD_SUFFIX = ".pdttpack"
_CRC_CHUNK = 8 << 20


def write_packed_shard(path: str, images_u8: np.ndarray,
                       labels: np.ndarray, meta: dict | None = None) -> dict:
    """Write ONE shard; returns its header dict. Atomic (tmp+rename):
    a killed pack job can never leave a half-shard that later opens."""
    images_u8 = np.ascontiguousarray(images_u8, np.uint8)
    labels = np.ascontiguousarray(labels, np.int32)
    if images_u8.ndim != 4:
        raise ValueError(f"images must be (n,H,W,C), got {images_u8.shape}")
    if len(images_u8) != len(labels):
        raise ValueError(
            f"{len(images_u8)} images vs {len(labels)} labels")
    crc = zlib.crc32(images_u8)
    crc = zlib.crc32(labels, crc)
    header = {
        "n": int(len(images_u8)),
        "shape": [int(s) for s in images_u8.shape[1:]],
        "image_dtype": "|u1",
        "label_dtype": "<i4",
        "crc32": int(crc & 0xFFFFFFFF),
        "meta": meta or {},
    }
    blob = json.dumps(header, sort_keys=True).encode()
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(MAGIC)
        f.write(struct.pack("<I", len(blob)))
        f.write(blob)
        f.write(images_u8)
        f.write(labels)
    os.replace(tmp, path)
    return header


def read_header(path: str) -> tuple[dict, int]:
    """→ (header dict, payload offset). Raises ValueError on a file that
    is not a packed shard (wrong magic / torn or truncated header) — one
    exception type, so cache-or-fallthrough callers can't be crashed by
    a half-copied shard."""
    with open(path, "rb") as f:
        magic = f.read(len(MAGIC))
        if magic != MAGIC:
            raise ValueError(f"{path}: not a packed shard (magic {magic!r})")
        raw = f.read(4)
        if len(raw) < 4:
            raise ValueError(f"{path}: truncated shard header")
        (hlen,) = struct.unpack("<I", raw)
        blob = f.read(hlen)
        if len(blob) < hlen:
            raise ValueError(f"{path}: truncated shard header")
        try:
            header = json.loads(blob)
        except ValueError as e:
            raise ValueError(f"{path}: corrupt shard header ({e})")
        if not isinstance(header, dict) or "n" not in header \
                or "shape" not in header or "crc32" not in header:
            raise ValueError(f"{path}: shard header missing fields")
        return header, len(MAGIC) + 4 + hlen


def verify_shard(path: str) -> bool:
    """Streaming CRC check of the whole payload against the header's
    crc32. Counts failures in ``packed_cache_crc_failures_total``."""
    header, off = read_header(path)
    crc = 0
    with open(path, "rb") as f:
        f.seek(off)
        while True:
            chunk = f.read(_CRC_CHUNK)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
    ok = (crc & 0xFFFFFFFF) == header["crc32"]
    if not ok:
        get_registry().counter(
            "packed_cache_crc_failures_total",
            help="packed-cache shards whose payload CRC mismatched the "
                 "header").inc()
    return ok


class PackedShardReader:
    """One shard: mmap'd image region + in-RAM labels."""

    def __init__(self, path: str, verify: bool = False):
        self.path = path
        self.header, off = read_header(path)
        if verify and not verify_shard(path):
            raise ValueError(f"{path}: payload CRC mismatch (corrupt "
                             "shard — re-run tools/pack_dataset.py)")
        n = self.header["n"]
        shape = tuple(self.header["shape"])
        self.images = np.memmap(path, dtype=np.uint8, mode="r",
                                offset=off, shape=(n,) + shape)
        lbl_off = off + n * int(np.prod(shape, dtype=np.int64))
        self.labels = np.fromfile(path, dtype=np.dtype(
            self.header["label_dtype"]), count=n, offset=lbl_off
        ).astype(np.int32)

    def __len__(self) -> int:
        return self.header["n"]


def find_shards(path_or_glob: str, split: str | None = None) -> list[str]:
    """Resolve a shard set: a directory, a glob, or one file. Sorted —
    shard order is part of the record-index contract.

    In a SPLIT-ORGANIZED directory (any ``train-*``/``val-*`` prefixed
    shard present) only the requested split's shards are returned — a
    missing split is an empty list (→ a loud cache MISS), never a
    silent fall-through to the other split's data (eval reading train
    pixels would inflate accuracy without any error). Directories of
    unprefixed shards (hand-assembled) serve every split."""
    if os.path.isdir(path_or_glob):
        all_shards = sorted(glob_mod.glob(os.path.join(
            path_or_glob, f"*{SHARD_SUFFIX}")))
        if split:
            split_organized = any(
                os.path.basename(s).startswith(("train-", "val-"))
                for s in all_shards)
            if split_organized:
                return [s for s in all_shards
                        if os.path.basename(s).startswith(f"{split}-")]
        return all_shards
    if os.path.isfile(path_or_glob):
        return [path_or_glob]
    return sorted(glob_mod.glob(path_or_glob))


class PackedImageDataset(U8ImageDataset):
    """Fixed-record packed shards as a batch-style dataset.

    The read stage is ONE strided gather against the mmap per shard
    touched; augment/normalize is the inherited U8ImageDataset path
    (native imgops when built), so batches are byte-identical to an
    in-RAM U8ImageDataset over the same pixels — decode simply no
    longer exists as a stage. Mean/std come from the pack-time meta
    (falling back to the ImageNet constants).
    """

    def __init__(self, shards: str | list[str], *, augment: bool,
                 pad: int = 4, randaugment=None, verify: bool = False,
                 raw_u8: bool = False, split: str | None = None,
                 mean: np.ndarray | None = None,
                 std: np.ndarray | None = None):
        paths = (find_shards(shards, split)
                 if isinstance(shards, str) else list(shards))
        if not paths:
            raise FileNotFoundError(
                f"no {SHARD_SUFFIX} shards under {shards!r}")
        self._paths = paths
        self._verify = verify
        self._readers = [PackedShardReader(p, verify=verify)
                         for p in paths]
        shapes = {tuple(r.header["shape"]) for r in self._readers}
        if len(shapes) != 1:
            raise ValueError(
                f"shards disagree on record shape: {sorted(shapes)}")
        self._shape = next(iter(shapes))
        counts = np.array([len(r) for r in self._readers], np.int64)
        self._starts = np.concatenate([[0], np.cumsum(counts)])[:-1]
        labels = np.concatenate([r.labels for r in self._readers])
        meta = self._readers[0].header.get("meta", {})
        if mean is None:
            mean = np.asarray(meta.get("mean", IMAGENET_MEAN), np.float32)
        if std is None:
            std = np.asarray(meta.get("std", IMAGENET_STD), np.float32)
        super().__init__(None, labels, mean, std, augment=augment,
                         pad=int(meta.get("pad", pad)),
                         randaugment=randaugment, raw_u8=raw_u8)
        self._c_read = get_registry().counter(
            "packed_cache_records_read_total",
            help="records served out of the packed pre-decoded cache")

    def __getstate__(self):
        # memmaps don't travel (grain worker processes pickle the
        # dataset; the shared-memory pool forks and never gets here):
        # reopen lazily from paths on the other side.
        state = super().__getstate__()
        state["_readers"] = None
        state["_c_read"] = None
        return state

    def _ensure_open(self):
        if self._readers is None:
            self._readers = [PackedShardReader(p, verify=False)
                             for p in self._paths]
        if self._c_read is None:
            self._c_read = get_registry().counter(
                "packed_cache_records_read_total",
                help="records served out of the packed pre-decoded cache")

    def _read_images(self, idx) -> np.ndarray:
        self._ensure_open()
        idx = np.asarray(idx, np.int64)
        out = np.empty((len(idx),) + self._shape, np.uint8)
        shard_ids = np.searchsorted(self._starts, idx, side="right") - 1
        for si in np.unique(shard_ids):
            m = shard_ids == si
            out[m] = self._readers[si].images[idx[m] - self._starts[si]]
        self._c_read.inc(len(idx))
        return out


def load_packed_if_present(cache_dir: str, split: str, *, augment: bool,
                           randaugment=None, verify: bool = False,
                           raw_u8: bool = False) -> PackedImageDataset | None:
    """Cache-or-fallthrough used by build_dataset: a valid cache for the
    split is a HIT (dataset returned), anything else — no dir, no
    shards, unreadable/corrupt shards — is a MISS (None returned; the
    caller builds the original decode-path dataset). Counted either way:
    a run silently falling back to the 3-6x slower decode path must at
    least be visible on /metrics."""
    hits = get_registry().counter(
        "packed_cache_hits_total",
        help="dataset builds served from a packed cache")
    misses = get_registry().counter(
        "packed_cache_misses_total",
        help="dataset builds that fell back to the decode path "
             "(no/invalid packed cache)")
    try:
        shards = find_shards(cache_dir, split)
        if not shards:
            misses.inc()
            return None
        ds = PackedImageDataset(shards, augment=augment,
                                randaugment=randaugment, verify=verify,
                                raw_u8=raw_u8)
    except (OSError, ValueError) as e:
        import sys

        print(f"[packed-cache] {cache_dir!r} ({split}): falling back to "
              f"decode path ({type(e).__name__}: {e})",
              file=sys.stderr, flush=True)
        misses.inc()
        return None
    hits.inc()
    return ds
