"""Real-text corpus pipeline: local files → tokens → packed blocks.

The reference's config 4 trains BERT MLM on Wikipedia text
(BASELINE.json:10) through a tokenize → pack → mask pipeline; the causal
configs consume packed next-token blocks the same way. This module is that
pipeline for LOCAL data (this environment has no network egress, and
production TPU pods mount data anyway):

- ``datasets: text_lm | text_mlm`` with ``data.text_files`` pointing at
  .txt/.jsonl globs;
- tokenizer: a HF tokenizer directory via ``data.tokenizer_path``
  (transformers.AutoTokenizer, loaded offline), else a built-in byte-level
  tokenizer (vocab 259: 256 bytes + pad/eos/mask) so the path works with
  zero assets;
- packing: documents are tokenized independently, joined with EOS, and cut
  into contiguous ``seq_len`` blocks — the standard LM packing that keeps
  every batch shape static (SURVEY §7.4.5);
- split: every ``eval_holdout``-th block goes to eval — deterministic,
  disjoint from train, no files to maintain.
"""

from __future__ import annotations

import glob as glob_mod
import json
import os

import numpy as np


class ByteTokenizer:
    """Asset-free fallback: UTF-8 bytes + {pad, eos, mask} specials."""

    vocab_size = 259
    pad_id = 256
    eos_id = 257
    mask_id = 258

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8",
                                                       errors="replace")


class HFTokenizer:
    """transformers.AutoTokenizer adapter (loaded from a LOCAL directory)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path)
        self.vocab_size = len(self._tok)
        self.pad_id = self._tok.pad_token_id or 0
        self.eos_id = (self._tok.eos_token_id
                       if self._tok.eos_token_id is not None
                       else self._tok.sep_token_id or 0)
        self.mask_id = (self._tok.mask_token_id
                        if self._tok.mask_token_id is not None
                        else self.eos_id)

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, ids) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)


def load_tokenizer(tokenizer_path: str = ""):
    return HFTokenizer(tokenizer_path) if tokenizer_path else ByteTokenizer()


def _doc_text(doc) -> str:
    return doc.get("text", "") if isinstance(doc, dict) else ""


def _iter_documents(files: list[str | tuple[str, int]]):
    """Yield text documents: .jsonl lines' 'text' field; .json whole-file
    (array of docs or a single doc); else raw lines grouped into
    blank-line-separated paragraphs (txt). A ``(path, repeat)`` entry
    yields the file's documents ``repeat`` times (corpus mixing — the
    data-blend "epochs per source" recipe; re-reads the file instead of
    holding it in RAM)."""
    for entry in files:
        path, repeat = entry if isinstance(entry, tuple) else (entry, 1)
        for _ in range(repeat):
            yield from _iter_one_file(path)


def _iter_one_file(path: str):
    with open(path, encoding="utf-8", errors="replace") as fh:
        if path.endswith(".jsonl"):
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if _doc_text(doc):
                    yield _doc_text(doc)
        elif path.endswith(".json"):
            # a standard (possibly pretty-printed) JSON file — parsing
            # it line-wise would silently contribute zero documents
            try:
                parsed = json.load(fh)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path} is not valid JSON: {e}") from e
            docs = parsed if isinstance(parsed, list) else [parsed]
            for doc in docs:
                if _doc_text(doc):
                    yield _doc_text(doc)
        else:
            para: list[str] = []
            for line in fh:
                if line.strip():
                    para.append(line.strip())
                elif para:
                    yield " ".join(para)
                    para = []
            if para:
                yield " ".join(para)


def pack_corpus(files: list[str], tokenizer, seq_len: int) -> np.ndarray:
    """Tokenize + pack into (N, seq_len) int32 blocks (EOS-joined docs;
    the ragged tail that doesn't fill a block is dropped — same contract
    as drop_last batching). Accumulates per-document int32 chunks, not one
    giant Python int list (~7x the final array's RAM)."""
    eos = np.asarray([tokenizer.eos_id], np.int32)
    chunks: list[np.ndarray] = []
    total = 0
    for doc in _iter_documents(files):
        ids = np.asarray(tokenizer.encode(doc), np.int32)
        chunks.extend((ids, eos))
        total += len(ids) + 1
    n_blocks = total // seq_len
    if n_blocks == 0:
        raise ValueError(
            f"corpus too small: {total} tokens < seq_len {seq_len}")
    stream = np.concatenate(chunks)[: n_blocks * seq_len]
    return stream.reshape(n_blocks, seq_len)


def _resolve_files(pattern: str) -> list[tuple[str, int]]:
    """``data.text_files`` spec → [(path, repeat)].

    Comma-separated globs, each optionally ``glob::N`` — that source's
    documents appear N times in the packed stream (integer data-blend
    weights, the "epochs per source" mixing recipe)."""
    out: list[tuple[str, int]] = []
    for spec in pattern.split(","):
        spec = spec.strip()
        if not spec:
            continue
        glob_part, _, rep_part = spec.partition("::")
        repeat = 1
        if rep_part:
            try:
                repeat = int(rep_part)
            except ValueError:
                repeat = -1
            if repeat < 1:
                raise ValueError(
                    f"text_files weight in {spec!r} must be a positive "
                    "integer (docs from that glob repeat N times)")
        files = sorted(glob_mod.glob(glob_part, recursive=True))
        if not files:
            raise FileNotFoundError(
                f"data.text_files matched nothing: {glob_part!r}")
        out.extend((f, repeat) for f in files)
    if not out:
        raise FileNotFoundError(
            f"data.text_files matched nothing: {pattern!r}")
    return out


def _split(blocks: np.ndarray, train: bool, eval_holdout: int):
    idx = np.arange(len(blocks))
    is_eval = (idx % eval_holdout) == (eval_holdout - 1)
    picked = blocks[~is_eval] if train else blocks[is_eval]
    if len(picked) == 0:  # tiny corpora: fall back to using everything
        picked = blocks
    return picked


# Trainer builds the train and eval datasets back-to-back; pack the corpus
# once and split the shared (read-only) array both ways. Keyed on content
# identity (paths + mtimes + sizes) so a changed corpus re-packs.
_PACK_CACHE: dict[tuple, np.ndarray] = {}


def _packed_blocks(files, tokenizer_path: str, seq_len: int):
    paths = [f if isinstance(f, str) else f[0] for f in files]
    key = (tuple(f if isinstance(f, str) else tuple(f) for f in files),
           tuple((os.path.getmtime(p), os.path.getsize(p)) for p in paths),
           tokenizer_path, seq_len)
    if key not in _PACK_CACHE:
        _PACK_CACHE.clear()  # hold at most one corpus
        tok = load_tokenizer(tokenizer_path)
        _PACK_CACHE[key] = pack_corpus(files, tok, seq_len)
    return _PACK_CACHE[key]


class TokenBinDataset:
    """Pre-tokenized flat binary token file, memory-mapped (the
    nanoGPT-style ``.bin`` format: one contiguous uint16/uint32 token
    stream). The scalable path for corpora too large to tokenize+pack in
    RAM at startup: the OS pages in only the blocks a batch touches.

    Blocks are the non-overlapping seq_len windows of the stream; batch
    reads copy out of the mmap (int32, C-contiguous) so downstream code
    never holds mmap views.
    """

    is_item_style = False

    def __init__(self, path: str, seq_len: int, dtype: str = "uint16",
                 train: bool = True, eval_holdout: int = 50,
                 vocab_size: int = 0):
        self.path = path
        self.dtype = dtype
        self.seq_len = seq_len
        self.vocab_size = vocab_size  # 0 → unchecked
        self._mm = np.memmap(path, dtype=np.dtype(dtype), mode="r")
        if len(self._mm) < seq_len:
            raise ValueError(
                f"token bin {path} has {len(self._mm)} tokens < seq_len "
                f"{seq_len}")
        n_blocks = len(self._mm) // seq_len
        self._blocks = _split(np.arange(n_blocks), train, eval_holdout)

    def __getstate__(self):
        # grain workers pickle the dataset; a pickled memmap materializes
        # the WHOLE file (the multi-GB case this class exists for). Reopen
        # in the worker instead.
        state = self.__dict__.copy()
        state["_mm"] = None
        return state

    def _mmap(self):
        if self._mm is None:
            self._mm = np.memmap(self.path, dtype=np.dtype(self.dtype),
                                 mode="r")
        return self._mm

    def __len__(self) -> int:
        return len(self._blocks)

    def get_batch(self, idx: np.ndarray, rng, train: bool) -> dict:
        mm = self._mmap()
        S = self.seq_len
        out = np.empty((len(idx), S), np.int32)
        for row, logical in enumerate(np.asarray(idx)):
            start = int(self._blocks[int(logical)]) * S
            out[row] = mm[start: start + S]
        if self.vocab_size and out.max() >= self.vocab_size:
            # checked per batch — scanning the whole mmap up-front would
            # page in the entire file; out-of-range ids would otherwise
            # reach the embedding gather and train on garbage silently
            raise ValueError(
                f"token id {int(out.max())} >= model vocab {self.vocab_size} "
                f"in {self.path}")
        return {"input_ids": out}


def write_token_bin(ids: np.ndarray, path: str, dtype: str = "uint16"):
    """Produce a TokenBinDataset file from a token id array (the offline
    tokenize step; also what tests use)."""
    info = np.iinfo(np.dtype(dtype))
    if ids.min() < info.min or ids.max() > info.max:
        raise ValueError(f"token ids out of range for {dtype}")
    np.asarray(ids, np.dtype(dtype)).ravel().tofile(path)


def build_text_dataset(data_cfg, model_cfg, train: bool, mlm: bool,
                       eval_holdout: int = 50):
    """Factory for datasets 'text_lm' (causal) and 'text_mlm' (BERT MLM).

    ``data.text_files`` matching a single ``.bin`` file selects the
    memory-mapped pre-tokenized path (causal only); anything else goes
    through tokenize-and-pack.
    """
    from pytorch_distributed_train_tpu.data.datasets import (
        ArrayDataset, MLMDataset,
    )

    files = _resolve_files(data_cfg.text_files)
    paths = [f for f, _ in files]
    n_bin = sum(p.endswith(".bin") for p in paths)
    if n_bin:
        if any(rep != 1 for _, rep in files):
            raise ValueError(
                "::N blend weights are not supported on .bin token files "
                "(the memory-mapped stream has no packing stage to repeat "
                "documents in) — drop the weight or use text files")
        if n_bin != len(paths):
            raise ValueError(
                f"text_files mixes .bin and text files ({paths}); the "
                "tokenize-and-pack path would read binary tokens as UTF-8 "
                "garbage — match exactly one .bin or only text files")
        if mlm:
            raise ValueError(
                "token-bin datasets are causal-LM only (MLM needs the "
                "tokenizer's mask id — use text files + tokenizer_path)")
        if len(paths) != 1:
            raise ValueError(
                f"expected one .bin token file, matched {len(paths)}")
        return TokenBinDataset(paths[0], data_cfg.seq_len,
                               dtype=data_cfg.token_bin_dtype,
                               train=train, eval_holdout=eval_holdout,
                               vocab_size=model_cfg.vocab_size)

    tok = load_tokenizer(data_cfg.tokenizer_path)
    if tok.vocab_size > model_cfg.vocab_size:
        raise ValueError(
            f"tokenizer vocab {tok.vocab_size} exceeds model.vocab_size "
            f"{model_cfg.vocab_size}")
    blocks = _packed_blocks(files, data_cfg.tokenizer_path, data_cfg.seq_len)
    blocks = _split(blocks, train, eval_holdout)
    if not mlm:
        return ArrayDataset({"input_ids": blocks})
    # random-replacement ids must come from the TOKENIZER's vocab — the
    # model's (padded) vocab may contain rows real data never produces.
    return MLMDataset(
        blocks, np.ones_like(blocks), tok.vocab_size,
        mlm_prob=data_cfg.mlm_prob, mask_id=tok.mask_id,
    )
