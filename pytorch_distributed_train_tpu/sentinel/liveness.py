"""Cross-host liveness plane: store heartbeats + coordinator hang monitor.

The failure mode utils/watchdog.py CANNOT see: one host wedges (stuck
DCN link, deadlocked collective, runaway host-side op) and every OTHER
host blocks inside the same collective. Each peer's local Heartbeat
monitor only knows its own steps stopped — it cannot say WHOSE fault
that is, and when every host aborts at its own local timeout the
post-mortem names nobody. This plane answers the attribution question:

- every host publishes ``{step, ts}`` heartbeats through the elastic
  launcher's KV store (elastic.worker_store) at step cadence, plus a
  background ``phase`` record carrying its currently-open trace spans
  (obs/spans.py ``active_all`` — readable even while the main thread is
  wedged, which is the whole point);
- the coordinator (env rank 0) runs a monitor thread that watches for a
  heartbeat going STALE — unchanged on the monitor's own clock for
  ``hang_timeout_s`` (receiver-side staleness: immune to clock skew) —
  then names the blamed host id and its open spans, sets a store key
  that makes EVERY host's watcher thread dump its flight recorder
  (cluster-wide post-mortem, not just the blamed host's), and exits
  with ``exit_code`` so the elastic agent's whole-gang restart turns a
  silent deadlock into a diagnosed, bounded-time outage.

Hosts that have never heartbeat are NOT blamed — a gang stuck in
first-compile must not be diagnosed as hung (init-phase wedges belong
to the local heartbeat / scheduler timeout). Identity comes from the
launcher env contract (``PROCESS_ID`` / ``NUM_PROCESSES`` /
``RESTART_GENERATION``), not jax.distributed, so the plane works in any
process tpurun spawns — including single-device workers in tests.

Store-resilience contracts (store_plane.py; docs/fault_tolerance.md
degraded-mode matrix):

- **Heartbeat publishes are time-bounded.** ``beat()`` deposits into a
  latest-wins slot drained by a background publisher thread and waits
  at most ``beat_timeout_s`` — a slow store can never stall the step
  loop. Beats that were superseded unsent, timed out, or failed are
  COUNTED (``store_beats_dropped_total{reason=}``), never blocking.
- **Blame is suspended during store outages.** A blackout makes every
  heartbeat look stale at once; dumping and restarting a healthy gang
  for that is the false-blame this plane exists to prevent. The
  monitor suspends blame while (a) the process-global store health is
  not ok, or (b) EVERY host it has ever seen heartbeat (two or more)
  is stale simultaneously — one host can hang alone, the whole gang
  going silent together is the store's signature. During suspension
  staleness clocks are re-baselined, so recovery re-arms blame with a
  full ``hang_timeout_s`` window (a genuinely hung host is re-detected
  after the outage, bounded-late, instead of insta-blamed). A gang
  TRULY deadlocked on every host falls to each host's local watchdog.
- **The watcher and monitor survive outages.** Store errors skip the
  iteration instead of killing the thread; the plane goes degraded,
  not dark.
"""

from __future__ import annotations

import json
import os
import threading
import time


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


class LivenessPlane:
    """Heartbeat publisher (every host) + hang monitor (rank 0).

    ``store_factory`` returns a NEW store client per call (StoreClient
    connections are not shared across threads); by default it is
    elastic.worker_store, which yields None outside a tpurun job — the
    plane then disables itself (``active`` False).
    """

    def __init__(self, *, hang_timeout_s: float, poll_s: float = 1.0,
                 exit_code: int = 43, every_steps: int = 1,
                 recorder=None, spans=None, store_factory=None,
                 rank: int | None = None, world: int | None = None,
                 gen: str | None = None, exit_fn=None,
                 beat_timeout_s: float = 0.25, store_health=None):
        from pytorch_distributed_train_tpu.elastic import worker_store
        from pytorch_distributed_train_tpu.store_plane import get_health

        self.hang_timeout_s = hang_timeout_s
        self.poll_s = max(0.05, poll_s)
        self.exit_code = exit_code
        self.every_steps = max(1, every_steps)
        self.beat_timeout_s = max(0.05, beat_timeout_s)
        self.recorder = recorder
        self.spans = spans
        self._factory = store_factory or worker_store
        self._health = store_health if store_health is not None else (
            get_health())
        self.rank = rank if rank is not None else _env_int("PROCESS_ID", 0)
        self.world = (world if world is not None
                      else _env_int("NUM_PROCESSES", 1))
        self.gen = gen if gen is not None else os.environ.get(
            "RESTART_GENERATION", "0")
        self._exit = exit_fn or (lambda rc: os._exit(rc))
        self._stop = threading.Event()
        self._dumped = False
        self._beat_store = None
        self._threads: list[threading.Thread] = []
        self.active = False
        self.blamed: dict | None = None  # monitor's diagnosis (rank 0)
        # latest-wins pending beat: (step, done-event); drained by the
        # lazily-started publisher thread (_publish_loop)
        self._pending: tuple[int, threading.Event] | None = None
        self._pending_lock = threading.Lock()
        self._pending_ev = threading.Event()
        self._publisher: threading.Thread | None = None
        self.suspended = False  # monitor blame-suspension state (rank 0)

    # ------------------------------------------------------------- keys
    def _key(self, kind: str, rank: int | None = None) -> str:
        base = f"sentinel/{self.gen}/{kind}"
        return base if rank is None else f"{base}/{rank}"

    def _mk_store(self, name: str, *, attempts: int = 2,
                  op_timeout_s: float = 0.5):
        from pytorch_distributed_train_tpu.faults.retry import RetryPolicy
        from pytorch_distributed_train_tpu.store_plane import ResilientStore

        return ResilientStore(
            self._factory, op_timeout_s=op_timeout_s,
            policy=RetryPolicy(max_attempts=attempts, base_delay_s=0.05,
                               max_delay_s=0.25, jitter=0.5,
                               retry_on=(OSError,)),
            health=self._health, name=name)

    # ---------------------------------------------------------- lifecycle
    def start(self) -> bool:
        """Connect and spawn the watcher (+ monitor on rank 0). Returns
        False (plane inactive) when no launcher store is reachable."""
        try:
            probe = self._factory()
        except OSError:
            probe = None
        if probe is None:
            return False
        try:
            probe.close()
        except Exception:
            pass
        # single attempt, small deadline: a failed beat is DROPPED and
        # counted (the next beat supersedes it), never retried into a
        # step-loop stall
        self._beat_store = self._mk_store("sentinel-beat", attempts=1)
        self.active = True
        watcher = threading.Thread(target=self._watch, daemon=True,
                                   name="sentinel-liveness-watch")
        watcher.start()
        self._threads.append(watcher)
        if self.rank == 0:
            monitor = threading.Thread(target=self._monitor, daemon=True,
                                       name="sentinel-hang-monitor")
            monitor.start()
            self._threads.append(monitor)
        return True

    def stop(self) -> None:
        self._stop.set()
        self._pending_ev.set()  # wake the publisher so it can exit
        for t in self._threads:
            t.join(timeout=2.0)
        if self._publisher is not None:
            self._publisher.join(timeout=2.0)
            self._publisher = None
        if self._beat_store is not None:
            try:
                self._beat_store.close()
            except Exception:
                pass
            self._beat_store = None
        self.active = False

    # ------------------------------------------------------------ publish
    def _count_dropped(self, reason: str) -> None:
        try:
            from pytorch_distributed_train_tpu.obs.registry import (
                get_registry,
            )

            get_registry().counter(
                "store_beats_dropped_total", labels={"reason": reason},
                help="liveness heartbeats not confirmed published: "
                     "superseded unsent, publish error, or slow store "
                     "(sentinel/liveness.py)").inc()
        except Exception:
            pass

    def _ensure_publisher(self) -> None:
        # caller holds _pending_lock
        if self._publisher is None or not self._publisher.is_alive():
            self._publisher = threading.Thread(
                target=self._publish_loop, daemon=True,
                name="sentinel-beat-publish")
            self._publisher.start()

    def _publish_loop(self) -> None:
        while not self._stop.is_set():
            if not self._pending_ev.wait(0.2):
                continue
            with self._pending_lock:
                item = self._pending
                self._pending = None
                self._pending_ev.clear()
            if item is None:
                continue
            step, done = item
            try:
                self._beat_store.set(
                    self._key("hb", self.rank),
                    json.dumps({"step": int(step),
                                "ts": time.time()}).encode())
            except Exception:
                self._count_dropped("error")
            finally:
                done.set()

    def _publish_hb(self, step: int) -> None:
        """Time-bounded publish: deposit latest-wins, wait at most
        ``beat_timeout_s`` for the publisher to confirm. A fast store
        behaves synchronously; a slow one costs the caller the bounded
        wait and the beat is counted dropped, not blocking."""
        if self._beat_store is None:
            return
        done = threading.Event()
        with self._pending_lock:
            if self._pending is not None:
                self._count_dropped("superseded")
                self._pending[1].set()  # release any bounded waiter
            self._pending = (int(step), done)
            self._ensure_publisher()
            self._pending_ev.set()
        if not done.wait(self.beat_timeout_s):
            self._count_dropped("slow_store")

    def beat(self, step: int) -> None:
        """Publish this host's heartbeat (call at step boundaries, main
        thread — a wedged step loop stops beating, which is the signal)."""
        self._last_step = step
        if not self.active or step % self.every_steps:
            return
        self._publish_hb(step)

    def pulse(self) -> None:
        """Heartbeat from OUTSIDE the step loop — eval batches, BN
        re-estimation, the final synchronized save. Liveness means "this
        host is making progress", not "a train step completed"; without
        these pulses any legitimately long non-step phase would go
        heartbeat-silent and the monitor would blame a healthy host."""
        if not self.active:
            return
        self._publish_hb(getattr(self, "_last_step", 0))

    def _open_spans(self) -> dict:
        if self.spans is None:
            return {}
        try:
            return self.spans.active_all()
        except Exception:
            return {}

    # ------------------------------------------------------------ watcher
    def _watch(self) -> None:
        """Every host: publish the phase record (open spans — readable
        while the main thread is wedged) and obey cluster-dump orders.
        Store errors skip the iteration — an outage degrades the plane,
        it must not kill the thread that would dump the post-mortem."""
        store = self._mk_store("liveness-watch")
        try:
            while not self._stop.wait(self.poll_s):
                try:
                    store.set(
                        self._key("phase", self.rank),
                        json.dumps({"spans": self._open_spans(),
                                    "ts": time.time()}).encode())
                    raw = store.get(self._key("dump"), timeout_ms=1)
                except TimeoutError:
                    continue  # no dump order pending
                except OSError:
                    continue  # store degraded: keep watching
                try:
                    self._dump_local(json.loads(raw.decode()))
                except ValueError:
                    continue  # corrupt order: ignore
        finally:
            try:
                store.close()
            except Exception:
                pass

    def _dump_local(self, order: dict) -> None:
        if self._dumped or self.recorder is None:
            return
        self._dumped = True
        try:
            from pytorch_distributed_train_tpu.obs import events as evl

            evl.emit("sentinel", "cluster_dump",
                     blamed=order.get("rank"))
        except Exception:
            pass
        try:
            self.recorder.dump(
                reason=f"cluster hang dump: host {order.get('rank')} "
                       f"blamed ({order.get('detail', '')})",
                suffix="_hang")
        except Exception:
            pass  # diagnostics must never crash the dump path

    # ------------------------------------------------------------ monitor
    def _set_suspended(self, value: bool, *, reason: str = "",
                       stale: int = 0) -> None:
        if value == self.suspended:
            return
        self.suspended = value
        name = "blame_suspended" if value else "blame_resumed"
        try:
            from pytorch_distributed_train_tpu.obs import events as evl

            evl.emit("store", name, reason=reason, stale_hosts=stale)
        except Exception:
            pass
        if value:
            print(f"[sentinel] hang blame SUSPENDED ({reason}): store "
                  "outage signature, not a host hang", flush=True)
        else:
            print("[sentinel] hang blame resumed (store recovered)",
                  flush=True)

    def _monitor(self) -> None:
        """Rank 0: receiver-side staleness over every host's heartbeat,
        with blame suspended while the outage signature holds (module
        doc). Survives store errors: an unreadable pass counts as
        outage evidence, never kills the thread."""
        from pytorch_distributed_train_tpu.obs.registry import get_registry

        store = self._mk_store("hang-monitor")
        # rank -> (last raw payload, last-change monotonic ts); hosts
        # enter only once they have heartbeat at least once.
        seen: dict[int, tuple[bytes, float]] = {}
        try:
            while not self._stop.wait(self.poll_s):
                now = time.monotonic()
                outage = not self._health.ok()
                changed = False
                stale_ranks: list[int] = []
                stale: tuple[int, float, bytes] | None = None
                raws: dict[int, bytes] = {}
                for r in range(self.world):
                    try:
                        raw = store.get(self._key("hb", r), timeout_ms=50)
                    except TimeoutError:
                        continue  # never started: not blamable (module doc)
                    except OSError:
                        outage = True  # unreadable ≠ unblamable host
                        continue
                    except Exception:
                        continue  # defensive: monitor must not die
                    raws[r] = raw
                    prev = seen.get(r)
                    if prev is None or prev[0] != raw:
                        seen[r] = (raw, now)
                        changed = True
                        continue
                    age = now - prev[1]
                    if age > self.hang_timeout_s:
                        stale_ranks.append(r)
                        if stale is None or age > stale[1]:
                            stale = (r, age, raw)
                # The store-outage signature: the store itself reports
                # trouble, or EVERY host ever seen (>=2) went stale at
                # once. One host can hang alone; the whole gang going
                # silent together means the control plane, and blaming
                # a healthy gang restarts it for nothing.
                all_stale = (len(seen) >= 2 and stale_ranks
                             and len(stale_ranks) == len(seen))
                if outage or (all_stale and not changed):
                    self._set_suspended(
                        True,
                        reason="store_degraded" if outage else "all_stale",
                        stale=len(stale_ranks))
                    # re-baseline: every staleness clock restarts, so
                    # recovery re-arms blame with a full window instead
                    # of insta-blaming whoever the outage froze first
                    for r, raw in raws.items():
                        seen[r] = (raw, now)
                    continue
                if self.suspended:
                    self._set_suspended(False)
                    continue  # freshly re-armed clocks: nothing stale yet
                if stale is None:
                    continue
                rank, age, raw = stale
                self._diagnose(store, rank, age, raw, get_registry())
                return
        finally:
            try:
                store.close()
            except Exception:
                pass

    def _diagnose(self, store, rank: int, age: float, raw: bytes,
                  registry) -> None:
        hb = {}
        try:
            hb = json.loads(raw.decode())
        except ValueError:
            pass
        phase: dict = {}
        try:
            phase = json.loads(store.get(
                self._key("phase", rank), timeout_ms=200).decode())
        except Exception:
            pass
        detail = (f"last step {hb.get('step')}, no heartbeat for "
                  f"{age:.1f}s, open spans {phase.get('spans') or {}}")
        self.blamed = {"rank": rank, "age_s": round(age, 1),
                       "step": hb.get("step"),
                       "spans": phase.get("spans") or {}}
        registry.counter(
            "sentinel_hangs_total",
            help="cross-host hangs diagnosed by the liveness monitor").inc()
        try:
            from pytorch_distributed_train_tpu.obs import events as evl

            evl.emit("sentinel", "hang_blamed", step=hb.get("step"),
                     rank=rank, age_s=round(age, 1),
                     spans=phase.get("spans") or {})
        except Exception:
            pass  # diagnostics must never block the restart
        print(f"[sentinel] host {rank} appears HUNG: {detail} — "
              f"triggering cluster flight-recorder dump and exiting "
              f"rc={self.exit_code} for gang restart", flush=True)
        if self.recorder is not None:
            try:
                self.recorder.record("hang_blamed", int(hb.get("step") or -1),
                                     rank=rank, age_s=round(age, 1))
            except Exception:
                pass
        try:
            store.set(self._key("dump"),
                      json.dumps({"rank": rank, "detail": detail}).encode())
        except Exception:
            pass
        # Let every host's watcher see the dump order (they poll at
        # poll_s), dump our own ring directly, then hand the outage to
        # the elastic agent via the distinct exit code.
        self._dump_local({"rank": rank, "detail": detail})
        time.sleep(min(3.0, 2 * self.poll_s))
        self._exit(self.exit_code)
