"""Numeric fault guard: loss-spike detection + the LR-cooldown transform.

The in-graph half of the guard lives in steps.py (``numeric_guard=True``
gates the optimizer update on all-finite grads/loss, the GradScaler
skip-step pattern generalized to unscaled training); this module holds
the HOST-side half the Trainer loop drives:

- ``SpikeDetector`` — a rolling window of recent healthy losses; a new
  loss is a spike when it deviates from the window median by more than
  ``spike_sigma`` robust standard deviations (MAD * 1.4826 — the robust
  sigma estimate, immune to the spike itself contaminating the
  statistic the way a mean/std window would be).
- ``cooldown_transform`` — an optax transform appended to the optimizer
  chain whose state carries a single LR scale factor. The auto-rewind
  path multiplies it down (``scale_cooldown``) AFTER restoring the
  checkpoint, so the replayed steps rerun at reduced LR — the standard
  divergence-recovery recipe (restore + cool down) without rebuilding
  or recompiling the jitted step: the factor is an opt_state leaf, a
  traced input, and it persists through subsequent checkpoints.
"""

from __future__ import annotations

from collections import deque
from typing import NamedTuple

# 1.4826 * MAD estimates sigma for a normal distribution; the constant
# makes spike_sigma readable as "standard deviations".
_MAD_TO_SIGMA = 1.4826


class SpikeDetector:
    """Rolling median+MAD divergence detector over HEALTHY losses.

    Only losses accepted as healthy enter the window — a diverging run
    must not drag the baseline up after it (that would let a slow ramp
    to 10x loss pass as 'normal'). ``spike_min_rel`` is an absolute
    floor on the deviation (relative to the median): early windows over
    near-identical losses have a near-zero MAD, and without the floor
    ordinary jitter would read as a many-sigma spike.
    """

    def __init__(self, window: int = 64, sigma: float = 6.0,
                 min_samples: int = 8, min_rel: float = 0.1):
        if window < 2:
            raise ValueError(f"spike window must be >= 2, got {window}")
        self.window: deque[float] = deque(maxlen=window)
        self.sigma = sigma
        self.min_samples = max(2, min_samples)
        self.min_rel = min_rel

    def is_spike(self, loss: float) -> bool:
        """Would ``loss`` be a spike against the current window? Does
        NOT add it — call ``add`` for losses judged healthy."""
        if len(self.window) < self.min_samples:
            return False
        xs = sorted(self.window)
        n = len(xs)
        med = (xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2]))
        devs = sorted(abs(x - med) for x in xs)
        mad = (devs[n // 2] if n % 2
               else 0.5 * (devs[n // 2 - 1] + devs[n // 2]))
        threshold = max(self.sigma * _MAD_TO_SIGMA * mad,
                        self.min_rel * abs(med))
        return abs(loss - med) > threshold

    def add(self, loss: float) -> None:
        self.window.append(loss)

    def reset(self) -> None:
        """Forget the window (after a rewind: the replayed region's
        losses re-enter from scratch — the pre-rewind tail may contain
        the very divergence being recovered from)."""
        self.window.clear()


class CooldownState(NamedTuple):
    """Optax state for ``cooldown_transform``: one replicated f32 scale."""

    scale: object  # jnp scalar; object-typed to keep jax out of cold paths


def cooldown_transform():
    """Optax transform scaling final updates by a stateful factor
    (1.0 = no-op). Appended LAST in the optimizer chain (like
    layer_lr_decay / reduce_on_plateau: scaling final updates == scaling
    the LR — before the optimizer, adam's normalization would undo it).
    The update never changes the factor itself; only the host-side
    rewind path does, via ``scale_cooldown``."""
    import jax
    import jax.numpy as jnp
    import optax

    def init(params):
        del params
        return CooldownState(scale=jnp.float32(1.0))

    def update(updates, state, params=None):
        del params
        updates = jax.tree.map(lambda u: u * state.scale.astype(u.dtype),
                               updates)
        return updates, state

    return optax.GradientTransformation(init, update)


def _map_cooldown(opt_state, fn):
    import jax

    return jax.tree.map(
        lambda s: fn(s) if isinstance(s, CooldownState) else s,
        opt_state, is_leaf=lambda s: isinstance(s, CooldownState))


def scale_cooldown(opt_state, factor: float):
    """Multiply the cooldown factor in an optimizer-state tree by
    ``factor`` (the rewind path calls this AFTER restore, so the factor
    compounds across repeated rewinds and survives in checkpoints).
    Returns the state unchanged when no cooldown transform is in the
    chain."""
    import jax.numpy as jnp

    return _map_cooldown(
        opt_state,
        lambda s: CooldownState(scale=s.scale * jnp.float32(factor)))


def cooldown_scale(opt_state) -> float | None:
    """Current cooldown factor, or None when the transform isn't in the
    chain — the logging hook (effective LR = schedule * plateau * this)."""
    hits: list = []
    _map_cooldown(opt_state, lambda s: (hits.append(s.scale), s)[1])
    if not hits:
        return None
    import numpy as np

    return float(np.asarray(hits[0]))
