"""Training health sentinel (ISSUE 3 tentpole): detect and recover from
the faults that DON'T crash.

PR 2's faults/ layer covers faults that kill a process (crash, SIGTERM,
corrupt checkpoint). The costliest production failures are quieter: a
non-finite gradient silently poisons the params, a diverging loss burns
thousands of steps before a human notices, and one wedged host deadlocks
every collective while each peer's LOCAL watchdog sees its own steps
still completing (it is blocked, not dead). Three planes close that gap:

- ``numeric``  — in-graph update gate (a non-finite grad/loss skips the
                 optimizer update, params unchanged), a rolling
                 median+MAD loss-spike detector, and the LR-cooldown
                 optax transform the auto-rewind path scales.
- rewind       — lives in the Trainer loop: after
                 ``sentinel.max_consecutive_bad`` bad steps it restores
                 the newest integrity-verified checkpoint
                 (faults/integrity ``latest_good_step``), fast-forwards
                 the data pipeline via the existing ``start_batch``
                 resume, and applies the LR cooldown.
- ``liveness`` — per-host ``{step, phase, ts}`` heartbeats through the
                 elastic launcher's store (elastic.worker_store) plus a
                 coordinator-side monitor that names the wedged host and
                 its open span, triggers a cluster-wide flight-recorder
                 dump, and exits with a distinct rc so the elastic
                 agent's gang restart bounds the outage.

Everything is counted in the obs registry
(``sentinel_skipped_steps_total{reason=}``, ``sentinel_rewinds_total``,
``sentinel_hangs_total``) and driven deterministically in tests by the
``step.nan`` / ``step.loss_spike`` / ``host.hang`` fault points
(faults/registry.py). docs/sentinel.md has the full story.
"""

from pytorch_distributed_train_tpu.sentinel.numeric import (  # noqa: F401
    CooldownState,
    SpikeDetector,
    cooldown_scale,
    cooldown_transform,
    scale_cooldown,
)
from pytorch_distributed_train_tpu.sentinel.liveness import (  # noqa: F401
    LivenessPlane,
)
