"""Online post-training plane: serving rollouts feed the trainer and
updated weights stream back onto the serving mesh with no storage
round-trip (docs/online_training.md).

Three pieces close the loop:

- ``rollouts``  — drives completion traffic through the serving plane
  (router or direct replica), harvesting prompt/completion/logprob
  records into versioned ``RolloutBatch``es tagged with the generating
  ``weight_version``, plus the GRPO-style conversion into train batches.
- ``publisher`` — seals the trainer's params at a step cadence via the
  ckpt shard wire format (``take_shard_snapshot`` → per-host CRC'd
  publish → ``assemble_shards``) onto the launcher KV store, and the
  fetch/reshard half a serving replica runs on swap.
- ``swap``      — the replica-side mutable weight-version state machine
  behind ``POST /admin/weights`` (tools/serve_http.py): a fetched and
  verified version is STAGED by the handler thread and APPLIED by the
  scheduler thread between decode quanta, so an in-flight request never
  observes a half-swapped model and never fails because of a swap.

``tools/online_loop.py`` wires the three into one supervised loop.
"""

from pytorch_distributed_train_tpu.online.publisher import (  # noqa: F401
    WeightPublisher,
    fetch_version,
    latest_meta,
    place_leaves,
    publish_version,
)
from pytorch_distributed_train_tpu.online.rollouts import (  # noqa: F401
    RolloutBatch,
    RolloutCollector,
    RolloutRecord,
    to_grpo_batch,
)
from pytorch_distributed_train_tpu.online.swap import (  # noqa: F401
    PendingSwap,
    WeightState,
)
