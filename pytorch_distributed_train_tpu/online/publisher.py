"""Trainer → serving weight publication over the launcher KV store.

The trainer seals its live params with the ckpt shard wire format
(ckpt/snapshot.py): each host ships only the array shards it OWNS as a
``part_<k>`` npz payload plus a CRC'd header, and any reader can
reassemble the GLOBAL flatten-order leaves with ``assemble_shards`` —
the format is mesh-agnostic, so a 1-proc serving replica restores a
2-proc trainer's params (and vice versa) bit-exactly, then
``place_leaves`` device_puts them into ITS mesh's shardings (the same
placement glue as ckpt/manager._place_leaves).

Wire layout (all keys under one namespace, chunking as ckpt/peer.py —
chunks land BEFORE the meta key, and metas before the seal: the store
has no transactions, write ordering is the atomicity)::

    wts/latest                 JSON: {version, step, hosts, sealed_at}
    wts/<ver>/sealed           same JSON, per version (fetch by version)
    wts/<ver>/<host>/meta      JSON: shard header + chunking info
    wts/<ver>/<host>/c<i>      payload chunks (<= CHUNK_BYTES each)

Versions are a monotonically increasing int assigned by the publisher
(NOT the trainer step — the step rides in the meta so replicas can
report lag in steps). The last ``KEEP_VERSIONS`` versions stay on the
store so a replica mid-fetch of version V survives V+1 landing; older
chunks are deleted after each seal.

Fault point ``weights.publish`` (faults/registry.py) traverses the
publish path; a corrupt chunk on the store is caught by the payload
CRC at fetch time and reads as "version unavailable" — the replica
keeps serving its current version (docs/fault_tolerance.md).
"""

from __future__ import annotations

import json
import time
import zlib

import jax
import numpy as np

from pytorch_distributed_train_tpu.ckpt import snapshot as snapshot_lib
from pytorch_distributed_train_tpu.faults import registry as faults_registry
from pytorch_distributed_train_tpu.obs import events as events_lib
from pytorch_distributed_train_tpu.obs.registry import get_registry

CHUNK_BYTES = 512 * 1024  # store get() buffers default to 1 MiB
KEEP_VERSIONS = 2  # newest + previous (in-flight fetch survives a seal)
_NS = "wts"


def _latest_key() -> str:
    return f"{_NS}/latest"


def _sealed_key(version: int) -> str:
    return f"{_NS}/{int(version)}/sealed"


def _meta_key(version: int, host: int) -> str:
    return f"{_NS}/{int(version)}/{int(host)}/meta"


def _chunk_key(version: int, host: int, i: int) -> str:
    return f"{_NS}/{int(version)}/{int(host)}/c{int(i)}"


def publish_shard(store, *, version: int, host: int, payload: bytes,
                  header: dict, chunk_bytes: int = CHUNK_BYTES) -> None:
    """One host's shard payload for ``version``: chunks first, then the
    meta naming them (a reader that sees meta can read every chunk)."""
    n_chunks = max(1, (len(payload) + chunk_bytes - 1) // chunk_bytes)
    for i in range(n_chunks):
        store.set(_chunk_key(version, host, i),
                  payload[i * chunk_bytes:(i + 1) * chunk_bytes])
    meta = dict(header)
    meta.update(n_chunks=n_chunks, payload_bytes=len(payload),
                payload_crc32=zlib.crc32(payload))
    store.set(_meta_key(version, host),
              json.dumps(meta, sort_keys=True).encode())


def seal_version(store, *, version: int, step: int, hosts) -> dict:
    """Flip ``wts/latest`` to ``version`` after every host's meta is in,
    then GC versions older than ``KEEP_VERSIONS``. Returns the seal
    record replicas read."""
    info = {"version": int(version), "step": int(step),
            "hosts": [int(h) for h in hosts], "sealed_at": time.time()}
    blob = json.dumps(info, sort_keys=True).encode()
    store.set(_sealed_key(version), blob)
    store.set(_latest_key(), blob)
    _gc_version(store, int(version) - KEEP_VERSIONS)
    return info


def _gc_version(store, version: int) -> None:
    if version < 1:
        return
    try:
        info = json.loads(store.get(_sealed_key(version),
                                    timeout_ms=50).decode())
    except Exception:
        return  # never sealed / already collected
    for host in info.get("hosts", []):
        try:
            meta = json.loads(store.get(_meta_key(version, host),
                                        timeout_ms=50).decode())
            for i in range(int(meta.get("n_chunks", 0))):
                store.delete(_chunk_key(version, host, i))
            store.delete(_meta_key(version, host))
        except Exception:
            continue  # best-effort housekeeping
    try:
        store.delete(_sealed_key(version))
    except Exception:
        pass


def publish_version(store, savable: dict, *, version: int, step: int,
                    owned_preds: dict | None = None,
                    chunk_bytes: int = CHUNK_BYTES) -> dict:
    """Seal + publish ``savable`` (checkpoint._savable layout, typically
    ``{"params": ...}``) as ``version``.

    Single-controller convenience covering every host in one call:
    ``owned_preds`` maps host id → shard-ownership predicate (tests and
    the online_loop driver simulate a multi-host trainer by partitioning
    device ids; ``{0: None}`` — the default — is the single-host job,
    owning every replica-0 shard). A real multi-host job calls
    ``publish_shard`` per process and ``seal_version`` on host 0 after a
    barrier, same split as ckpt/peer.py.
    """
    faults_registry.maybe_fire("weights.publish", step=step)
    preds = owned_preds if owned_preds else {0: None}
    for host, pred in preds.items():
        payload, header = snapshot_lib.take_shard_snapshot(
            savable, step=step, meta={"weight_version": int(version)},
            origin="online", owned=pred)
        publish_shard(store, version=version, host=host, payload=payload,
                      header=header, chunk_bytes=chunk_bytes)
    info = seal_version(store, version=version, step=step,
                        hosts=list(preds))
    get_registry().counter(
        "weights_published_total",
        help="weight versions sealed onto the online publish "
             "plane").inc()
    events_lib.emit("weights", "publish", step=step,
                    version=int(version), hosts=len(preds))
    return info


def latest_meta(store) -> dict | None:
    """The newest seal record {version, step, hosts, sealed_at}, or None
    when nothing has been published."""
    try:
        return json.loads(store.get(_latest_key(), timeout_ms=50).decode())
    except Exception:
        return None


def _fetch_host(store, version: int, host: int,
                chunk_timeout_ms: int) -> tuple[bytes, dict] | None:
    """One host's (payload, header) for ``version``, CRC-verified end to
    end — a corrupt or torn transfer reads as "not found"."""
    try:
        meta = json.loads(store.get(_meta_key(version, host),
                                    timeout_ms=50).decode())
    except Exception:
        return None
    if not meta.get("sealed") or meta.get("shard_format") != 1:
        return None
    chunks = []
    try:
        for i in range(int(meta["n_chunks"])):
            chunks.append(store.get(_chunk_key(version, host, i),
                                    timeout_ms=chunk_timeout_ms))
    except Exception:
        return None
    payload = b"".join(chunks)
    if (len(payload) != int(meta["payload_bytes"])
            or zlib.crc32(payload) != int(meta["payload_crc32"])):
        return None
    return payload, meta


def fetch_version(store, version: int | None = None, *,
                  chunk_timeout_ms: int = 10_000):
    """Replica-side fetch: ``(info, leaves, header)`` — the seal record,
    GLOBAL flatten-order numpy leaves (every host's shards reassembled
    and per-part CRC-verified by ``assemble_shards``), and the shard
    header — or None when the version is unsealed, incomplete, or any
    byte fails its CRC. None NEVER means "partially applied": the
    caller keeps serving its current weights."""
    try:
        key = _latest_key() if version is None else _sealed_key(version)
        info = json.loads(store.get(key, timeout_ms=50).decode())
    except Exception:
        return None
    fetched = []
    for host in info.get("hosts", []):
        got = _fetch_host(store, int(info["version"]), int(host),
                          chunk_timeout_ms)
        if got is None:
            return None
        fetched.append(got)
    assembled = snapshot_lib.assemble_shards(fetched)
    if assembled is None:
        return None
    leaves, header = assembled
    return info, leaves, header


def place_leaves(template, leaves: list[np.ndarray]):
    """Host leaves → device arrays in ``template``'s shardings (the
    serving mesh's layout), rebuilt into the template's structure — the
    ckpt/manager._place_leaves placement glue without the TrainState
    wrapper. None on any count/shape/dtype mismatch (e.g. a quantized
    serving tree): the caller rejects the swap instead of serving a
    half-cast model."""
    t_leaves, treedef = jax.tree_util.tree_flatten(template)
    if not snapshot_lib.leaves_match_template(leaves, t_leaves):
        return None
    tree = jax.tree_util.tree_unflatten(treedef, leaves)
    try:
        return jax.tree.map(
            lambda t, h: jax.device_put(h, getattr(t, "sharding", None)),
            template, tree)
    except (ValueError, TypeError) as e:
        print(f"[online] weight placement failed "
              f"({type(e).__name__}: {e}); keeping current weights",
              flush=True)
        return None


class WeightPublisher:
    """Cadence wrapper the trainer step loop holds: every
    ``cadence_steps`` steps, seal the live params as the next version.

    ``store`` may be None (no TPUSTORE_ADDR — e.g. a unit test trainer):
    ``maybe_publish`` is then a no-op returning None, same stance as
    ckpt/peer publication outside a tpurun job.
    """

    def __init__(self, store, *, cadence_steps: int = 10,
                 owned_preds: dict | None = None,
                 chunk_bytes: int = CHUNK_BYTES):
        if cadence_steps < 1:
            raise ValueError("cadence_steps must be >= 1")
        self.store = store
        self.cadence_steps = int(cadence_steps)
        self.owned_preds = owned_preds
        self.chunk_bytes = int(chunk_bytes)
        self.version = 0  # last published (0 = nothing yet)
        self.published_step = -1

    def due(self, step: int) -> bool:
        return (self.store is not None
                and int(step) >= self.published_step + self.cadence_steps)

    def publish(self, savable: dict, *, step: int) -> int:
        """Unconditionally publish as the next version; returns it."""
        version = self.version + 1
        publish_version(self.store, savable, version=version,
                        step=int(step), owned_preds=self.owned_preds,
                        chunk_bytes=self.chunk_bytes)
        self.version = version
        self.published_step = int(step)
        return version

    def maybe_publish(self, savable: dict, *, step: int) -> int | None:
        if not self.due(step):
            return None
        return self.publish(savable, step=step)
