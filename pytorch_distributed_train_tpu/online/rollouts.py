"""Rollout harvesting: serving completions → versioned train batches.

The collector is a plain HTTP client of the serving plane — it speaks
the same ``/v1/completions`` contract as any user, through the router
or a direct replica, so rollout traffic exercises exactly the
production request path (admission, deadlines, tracing). Each sampled
completion comes back stamped with the ``weight_version`` that
generated it (tools/serve_http.py attaches the version current at
submit time), so a batch spanning a live swap is visibly mixed-version
rather than silently stale: ``RolloutBatch.weight_version`` is the
dominant generating version and ``versions()`` the full census.

Group sampling (``group_size`` completions per prompt via the serving
``n=`` fan-out, sharing one prefill) feeds the GRPO-style conversion
``to_grpo_batch``: rewards are normalized WITHIN each prompt group
(advantage = (r - mean) / std), so the train signal is "better than
the other samples of this prompt", needing no learned value baseline.

Fault point ``rollout.fetch`` (faults/registry.py) traverses every
collection request; callers wrap ``collect`` in faults/retry.py's
``retry_call`` — transport errors (urllib raises OSError subclasses)
retry and then surface, they never poison a train step with a partial
batch.
"""

from __future__ import annotations

import dataclasses
import json
import time
import urllib.request

import numpy as np

from pytorch_distributed_train_tpu.faults import registry as faults_registry
from pytorch_distributed_train_tpu.obs import events as events_lib
from pytorch_distributed_train_tpu.obs.registry import get_registry


@dataclasses.dataclass
class RolloutRecord:
    """One sampled completion, tagged with what generated it."""

    prompt: str
    completion: str
    finish_reason: str
    weight_version: str  # serving-side version at submit time
    group: int  # prompt-group id (group-relative advantage)
    logprobs: list | None = None  # serving-side per-token logprobs


@dataclasses.dataclass
class RolloutBatch:
    """An ordered harvest of rollout records, version-tagged."""

    records: list
    collected_at: float = dataclasses.field(default_factory=time.time)

    def __len__(self) -> int:
        return len(self.records)

    def versions(self) -> dict[str, int]:
        """Generating weight_version → record count."""
        out: dict[str, int] = {}
        for r in self.records:
            out[r.weight_version] = out.get(r.weight_version, 0) + 1
        return out

    @property
    def weight_version(self) -> str:
        """The dominant generating version (ties break to the newest
        insertion — irrelevant in practice: a batch spans at most one
        swap boundary)."""
        census = self.versions()
        if not census:
            return ""
        return max(census, key=census.get)


class RolloutCollector:
    """Drives completion traffic through the serving plane and harvests
    the responses. ``base_url`` is a router or replica root
    (``http://host:port``); ``traceparent`` headers propagate the
    driver's trace so rollout requests land in its causal chain."""

    def __init__(self, base_url: str, *, group_size: int = 4,
                 max_tokens: int = 16, temperature: float = 0.9,
                 timeout_s: float = 30.0):
        if group_size < 1:
            raise ValueError("group_size must be >= 1")
        self.base_url = base_url.rstrip("/")
        self.group_size = int(group_size)
        self.max_tokens = int(max_tokens)
        self.temperature = float(temperature)
        self.timeout_s = float(timeout_s)

    def _post_json(self, path: str, obj: dict,
                   traceparent: str | None = None) -> dict:
        # `rollout.fetch` fault point: an injected transport fault is an
        # OSError, exactly what a dead replica raises — the caller's
        # retry_call wrapper sees both identically.
        faults_registry.maybe_fire("rollout.fetch")
        body = json.dumps(obj).encode()
        headers = {"Content-Type": "application/json"}
        if traceparent:
            headers["traceparent"] = traceparent
        req = urllib.request.Request(self.base_url + path, data=body,
                                     headers=headers)
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            return json.loads(resp.read())

    def sample_group(self, prompt: str, group: int,
                     traceparent: str | None = None) -> list[RolloutRecord]:
        """``group_size`` sampled completions of one prompt (the serving
        ``n=`` fan-out shares the prefill across the group)."""
        obj = {"prompt": prompt, "max_tokens": self.max_tokens,
               "temperature": self.temperature, "logprobs": True}
        if self.group_size > 1:
            obj["n"] = self.group_size
        out = self._post_json("/v1/completions", obj, traceparent)
        version = str(out.get("weight_version", ""))
        choices = out.get("choices") or [out]
        return [RolloutRecord(prompt=prompt,
                              completion=str(c.get("text", "")),
                              finish_reason=str(c.get("finish_reason", "")),
                              weight_version=version, group=group,
                              logprobs=c.get("logprobs"))
                for c in choices]

    def collect(self, prompts, *,
                traceparent: str | None = None) -> RolloutBatch:
        """One rollout batch: a group per prompt, in order."""
        records: list[RolloutRecord] = []
        for gid, prompt in enumerate(prompts):
            records.extend(self.sample_group(prompt, gid, traceparent))
        batch = RolloutBatch(records=records)
        get_registry().counter(
            "rollout_batches_total",
            help="rollout batches harvested from the serving "
                 "plane").inc()
        events_lib.emit("weights", "rollout_batch",
                        records=len(records),
                        version=batch.weight_version or "?")
        return batch


def group_advantages(rewards: dict[int, list[float]],
                     eps: float = 1e-6) -> dict[int, list[float]]:
    """Per-group (reward - mean) / std — the GRPO baseline. A group
    whose rewards are all equal gets zero advantage (no signal, no
    noise) rather than a 0/0."""
    out: dict[int, list[float]] = {}
    for gid, rs in rewards.items():
        arr = np.asarray(rs, np.float32)
        std = float(arr.std())
        mean = float(arr.mean())
        if std < eps:
            out[gid] = [0.0] * len(rs)
        else:
            out[gid] = [float((r - mean) / std) for r in arr]
    return out


def to_grpo_batch(batch: RolloutBatch, encode, reward_fn, *,
                  seq_len: int, pad_id: int = 0) -> dict:
    """RolloutBatch → numpy train batch for losses.make_grpo_loss.

    ``encode`` is the TRAINER's tokenizer (ids may differ from the
    serving tokenizer's only in implementation, not vocab); the prompt
    is re-encoded alone to find where completion positions start, so
    ``loss_mask`` covers exactly the sampled tokens. ``reward_fn:
    (prompt, completion) -> float`` scores each record; advantages are
    group-relative (``group_advantages``). Static shapes: every row
    pads/truncates to ``seq_len``.

    Returns {'input_ids': (N,S) int32, 'loss_mask': (N,S) float32,
    'advantage': (N,) float32}.
    """
    rewards: dict[int, list[float]] = {}
    for r in batch.records:
        rewards.setdefault(r.group, []).append(
            float(reward_fn(r.prompt, r.completion)))
    advs = group_advantages(rewards)
    cursor = {gid: 0 for gid in advs}
    ids = np.full((len(batch.records), seq_len), pad_id, np.int32)
    mask = np.zeros((len(batch.records), seq_len), np.float32)
    adv = np.zeros((len(batch.records),), np.float32)
    for row, r in enumerate(batch.records):
        p_ids = list(encode(r.prompt))
        full = p_ids + list(encode(r.completion))
        full = full[:seq_len]
        ids[row, : len(full)] = full
        mask[row, min(len(p_ids), seq_len): len(full)] = 1.0
        k = cursor[r.group]
        cursor[r.group] += 1
        adv[row] = advs[r.group][k]
    _record_batch_analytics(batch, rewards, adv)
    return {"input_ids": ids, "loss_mask": mask, "advantage": adv}


def _record_batch_analytics(batch: RolloutBatch, rewards: dict,
                            adv: np.ndarray) -> None:
    """Post-training health gauges per converted batch (the model-health
    plane's rollout-side inputs — obs/model_health.py): raw reward
    level/spread (``reward_collapse`` alert input), post-normalization
    advantage spread (all-zero = every group degenerate: no train
    signal), and the mixed-version census (sustained >1 = swap cadence
    lagging the harvest cadence). Host-side numpy on values already in
    hand — no extra work at scale."""
    flat = np.asarray([r for rs in rewards.values() for r in rs],
                      np.float32)
    reg = get_registry()
    if flat.size:
        reg.gauge("rollout_reward_mean",
                  help="mean raw reward over the last converted rollout "
                       "batch").set(float(flat.mean()))
        reg.gauge("rollout_reward_std",
                  help="raw reward spread over the last converted "
                       "rollout batch").set(float(flat.std()))
    if adv.size:
        reg.gauge("rollout_advantage_mean",
                  help="mean group-relative advantage of the last "
                       "converted rollout batch (~0 by "
                       "construction)").set(float(adv.mean()))
        reg.gauge("rollout_advantage_std",
                  help="advantage spread of the last converted rollout "
                       "batch (0 = no train signal)").set(
                      float(adv.std()))
    reg.gauge("rollout_mixed_versions",
              help="distinct generating weight versions in the last "
                   "converted rollout batch").set(
                  float(len(batch.versions())))
