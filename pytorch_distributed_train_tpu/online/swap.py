"""Replica-side weight-swap state machine (tools/serve_http.py).

``WeightState`` is the ONE mutable home of a serving process's weight
version — ``--weight-version`` seeds it at boot and every live swap
advances it, so /healthz, span correlation tags and completion
responses all read the same moving value (the frozen-at-boot version
was the bug this plane fixes).

Two-thread protocol, mirroring the service's submit/step split:

- the ``POST /admin/weights`` HANDLER thread fetches + CRC-verifies the
  published version and prepares the placed params OFF the scheduler
  lock (the expensive half), then ``stage()``s a pending swap and waits;
- the SCHEDULER thread calls ``apply_pending()`` between decode quanta
  (under the service lock, where nothing is mid-forward): the apply is
  a cheap attribute flip, so in-flight requests straddle the swap
  without failing — they simply complete at the version they were
  admitted under, observable via the ``weight_version`` stamped on
  their responses and spans.

A verify/fetch failure never reaches ``stage()``: the replica keeps
serving its current version (docs/fault_tolerance.md, ``weights.swap``
row). Only one swap stages at a time — a second concurrent POST gets
"busy" and retries.
"""

from __future__ import annotations

import dataclasses
import threading
import time

from pytorch_distributed_train_tpu.obs import events as events_lib
from pytorch_distributed_train_tpu.obs import spans as spans_lib
from pytorch_distributed_train_tpu.obs.registry import get_registry


@dataclasses.dataclass
class PendingSwap:
    version: str
    step: int
    apply_fn: object  # zero-arg callable flipping the params, or None
    t0: float  # monotonic, at fetch start (the swap-latency clock)
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    error: str | None = None
    duration_s: float = 0.0


class WeightState:
    """Mutable weight version + the staged-swap slot. Every critical
    section is a field read/write — the lock is never held across the
    apply, metrics, or journaling (the scheduler calls those unlocked:
    it is the only applier)."""

    def __init__(self, version: str = "0", step: int = 0):
        self._lock = threading.Lock()
        self._version = str(version)
        self._step = int(step)
        self._published_version = 0  # newest seen on the publish plane
        self._published_step = -1
        self._swaps = 0
        self._rejects = 0
        self._last_swap_wall = 0.0
        self._pending: PendingSwap | None = None

    # ------------------------------------------------------------ reads
    @property
    def version(self) -> str:
        with self._lock:
            return self._version

    def snapshot(self) -> dict:
        """The /healthz ``weights`` section."""
        with self._lock:
            out = {"version": self._version, "step": self._step,
                   "published_version": self._published_version,
                   "published_step": self._published_step,
                   "lag_steps": self._lag_locked(),
                   "swaps": self._swaps, "rejects": self._rejects,
                   "last_swap_age_s": (
                       round(time.time() - self._last_swap_wall, 3)
                       if self._last_swap_wall else None),
                   "pending": self._pending is not None}
        return out

    def _lag_locked(self) -> int | None:
        if self._published_step < 0:
            return None
        return max(0, self._published_step - self._step)

    # ---------------------------------------------------------- updates
    def note_published(self, version: int, step: int) -> None:
        """Record the publish plane's newest (version, step) — every
        swap POST carries it, so the lag gauge stays fresh even when
        the swap itself is a no-op."""
        with self._lock:
            self._published_version = max(self._published_version,
                                          int(version))
            self._published_step = max(self._published_step, int(step))
            lag = self._lag_locked()
        if lag is not None:
            _lag_gauge().set(lag)

    def reject(self, version, reason: str) -> None:
        """A fetch/verify/placement failure: count + journal it; the
        serving version is untouched."""
        with self._lock:
            self._rejects += 1
            current = self._version
        events_lib.emit("weights", "swap_rejected", version=str(version),
                        reason=reason, serving=current)

    def stage(self, pending: PendingSwap) -> bool:
        """Park a verified swap for the scheduler. False when another
        swap is already staged (caller answers "busy")."""
        with self._lock:
            if self._pending is not None:
                return False
            self._pending = pending
        return True

    def apply_pending(self) -> bool:
        """Scheduler-thread entry, between decode quanta: flip the
        params (if any), advance the version, re-stamp the span
        correlation tag, record latency + lag, wake the handler."""
        with self._lock:
            p = self._pending
            if p is None:
                return False
            self._pending = None
            old = self._version
        if p.apply_fn is not None:
            try:
                p.apply_fn()
            except Exception as e:  # noqa: BLE001 — reject, keep serving
                p.error = f"{type(e).__name__}: {e}"
                self.reject(p.version, f"apply: {p.error}")
                p.done.set()
                return False
        dur = time.monotonic() - p.t0
        with self._lock:
            self._version = str(p.version)
            self._step = int(p.step)
            self._swaps += 1
            self._last_swap_wall = time.time()
            lag = self._lag_locked()
        # every span recorded from here on carries the NEW version —
        # the old/new tag flip the timeline report keys on
        spans_lib.set_correlation_tags(weight_version=str(p.version))
        get_registry().histogram(
            "weight_swap_seconds",
            help="fetch→verify→place→apply latency of a live weight "
                 "swap").observe(dur)
        if lag is not None:
            _lag_gauge().set(lag)
        events_lib.emit("weights", "swap", version=str(p.version),
                        step=int(p.step), old_version=old,
                        dur_s=round(dur, 6))
        p.duration_s = dur
        p.done.set()
        return True


def _lag_gauge():
    return get_registry().gauge(
        "replica_weight_lag_steps",
        help="trainer's newest published step minus this replica's "
             "serving step (0 = fresh; each replica reports its own, "
             "scraped per-target)")
