"""Device-mesh construction.

The reference builds communicator *groups* at runtime
(torch:distributed/distributed_c10d.py:1984 `_new_process_group_helper`,
SURVEY C1/C2); on TPU the analogue is a static ``jax.sharding.Mesh`` whose
named axes ride the ICI torus. One mesh, four axes, unused axes sized 1 —
parallelism strategy becomes pure config (SURVEY §7.2).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

MESH_AXES = ("stage", "data", "fsdp", "expert", "tensor", "context")


def mesh_shape_from_config(mesh_cfg, n_devices: int | None = None) -> dict[str, int]:
    """Resolve axis sizes, expanding a single ``-1`` to fill the device count.

    Mirrors the ergonomics of torchrun's ``--nproc-per-node=auto``
    (torch:distributed/run.py:985): the common case is "use everything".
    """
    if n_devices is None:
        n_devices = jax.device_count()
    sizes = {ax: getattr(mesh_cfg, ax) for ax in MESH_AXES}
    wild = [ax for ax, s in sizes.items() if s == -1]
    if len(wild) > 1:
        raise ValueError(f"at most one mesh axis may be -1, got {wild}")
    fixed = int(np.prod([s for s in sizes.values() if s != -1]))
    if wild:
        if n_devices % fixed != 0:
            raise ValueError(
                f"device count {n_devices} not divisible by fixed axes {sizes}"
            )
        sizes[wild[0]] = n_devices // fixed
    total = int(np.prod(list(sizes.values())))
    if total != n_devices:
        raise ValueError(
            f"mesh {sizes} needs {total} devices but {n_devices} are available"
        )
    return sizes


def _hybrid_split(shape: tuple[int, ...],
                  n_slices: int) -> tuple[tuple[int, ...], tuple[int, ...]]:
    """Split a global mesh shape into (per-slice ICI shape, DCN shape).

    Elementwise ici*dcn == shape. The DCN factor goes on the OUTERMOST
    axis (MESH_AXES order) that divides the slice count — 'stage' first
    (pipeline P2P is the most DCN-tolerant traffic), then 'data'
    (gradient all-reduce is latency-tolerant). Landing on tensor/context
    warns loudly (per-layer collectives over DCN — a config smell);
    raises when no axis divides.
    """
    for i, s in enumerate(shape):
        if s >= n_slices and s % n_slices == 0:
            if MESH_AXES[i] in ("tensor", "context"):
                # Divisible, but only by a latency-critical axis: per-layer
                # TP/CP collectives over DCN run orders of magnitude slower
                # than ICI. Proceed (correctness is unaffected) but say so
                # loudly — the config, not this split, is what's wrong.
                import warnings

                warnings.warn(
                    f"multi-slice DCN factor landed on the "
                    f"latency-critical '{MESH_AXES[i]}' axis "
                    f"({dict(zip(MESH_AXES, shape))}, {n_slices} slices): "
                    "every per-layer collective will cross DCN. Give "
                    "stage/data/fsdp a multiple of the slice count.")
            ici = list(shape)
            ici[i] = s // n_slices
            dcn = [1] * len(shape)
            dcn[i] = n_slices
            return tuple(ici), tuple(dcn)
    raise ValueError(
        f"no mesh axis in {dict(zip(MESH_AXES, shape))} divisible by the "
        f"{n_slices} slices — put stage/data parallelism across slices")


def device_grid(shape: tuple[int, ...], devices) -> "np.ndarray":
    """Topology-aware device placement for the mesh axes.

    The analogue of NCCL's ring/tree graph construction from the physical
    fabric (torch:include/torch/csrc/distributed/c10d/ProcessGroupNCCL.hpp:315
    builds communicator topology at init): on real TPU backends
    ``mesh_utils.create_device_mesh`` reads chip coordinates and lays the
    innermost axes on neighbor ICI links (a naive ``jax.devices()`` reshape
    is only adjacency-correct by accident beyond one host — the
    latency-critical 'tensor'/'context' axes could land on non-neighbor
    chips). Multi-slice (DCN-connected) device sets route through
    ``create_hybrid_device_mesh`` with the slice factor on the outermost
    divisible axis (see _hybrid_split). Fake CPU test devices keep the
    plain reshape — they have no topology and the identity order keeps
    tests deterministic.
    """
    devs = list(devices)
    if getattr(devs[0], "platform", "cpu") == "cpu":
        return np.asarray(devs).reshape(shape)
    from jax.experimental import mesh_utils

    n_slices = len({getattr(d, "slice_index", 0) for d in devs})
    if n_slices > 1:
        # Outside the try: an indivisible slice count is a CONFIG error
        # with an actionable message — it must reach the user, not be
        # downgraded to the torus-assignment fallback below.
        ici, dcn = _hybrid_split(shape, n_slices)
    try:
        if n_slices > 1:
            return mesh_utils.create_hybrid_device_mesh(
                ici, dcn, devices=devs)
        return mesh_utils.create_device_mesh(shape, devices=devs)
    except (ValueError, NotImplementedError) as e1:
        # First escalation: many logical axes over few physical torus
        # dims (the 6-axis mesh on a 4x4 v5e raises NotImplementedError
        # unless physical axes may split) — still topology-aware.
        err = f"first attempt: {e1}"
        try:
            if n_slices <= 1:
                return mesh_utils.create_device_mesh(
                    shape, devices=devs, allow_split_physical_axes=True)
        except (ValueError, NotImplementedError) as e2:
            err += f"; split-axes escalation: {e2}"
    # Unmappable shape for this physical topology: train with the naive
    # order rather than not at all — correctness is unaffected, only
    # collective locality.
    import warnings

    warnings.warn(f"topology-aware mesh assignment failed ({err}); "
                  "falling back to enumeration order")
    return np.asarray(devs).reshape(shape)


def build_mesh(mesh_cfg=None, devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Build the global mesh.

    Axis order matters for ICI locality: ``stage`` outermost (pipeline P2P is
    the most DCN-tolerant traffic pattern of all the parallelisms), then
    ``data`` (cross-slice tolerant — gradient all-reduce is latency-tolerant),
    ``tensor``/``context`` innermost (latency-critical per-layer collectives
    ride neighbor ICI links). This is the layout recipe from the scaling-book
    mental model; :func:`device_grid` realizes it against the physical
    topology on real backends.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(np.asarray(devices).reshape(-1))
    if mesh_cfg is None:
        sizes = {ax: 1 for ax in MESH_AXES}
        sizes["data"] = len(devices)
    else:
        sizes = mesh_shape_from_config(mesh_cfg, len(devices))
    shape = tuple(sizes[ax] for ax in MESH_AXES)
    return Mesh(device_grid(shape, devices), MESH_AXES)


@dataclasses.dataclass(frozen=True)
class ActivationSharding:
    """Sequence-dim activation anchoring for SP / CP (SURVEY §2.3 SP row).

    Megatron-style sequence parallelism shards the activations BETWEEN
    tensor-parallel matmuls (norms, residuals, dropout) along the sequence
    dim; re-entering a TP matmul then costs an all-gather and leaving it a
    reduce-scatter — exactly the Megatron SP communication pattern, except
    GSPMD inserts the collectives from these constraints instead of the
    module rewrites torch uses (torch:distributed/tensor/parallel/style.py
    SequenceParallel). ``seq_axes`` may combine 'context' (ring/Ulysses CP)
    with 'tensor' (SP): the sequence dim then shards over both.
    """

    mesh: Mesh
    seq_axes: tuple[str, ...]
    batch_axes: tuple[str, ...] = ("data", "fsdp")

    def constrain(self, x):
        """Anchor (B, S, ...) activations; no-op when S can't divide."""
        import jax

        n = int(np.prod([self.mesh.shape[a] for a in self.seq_axes]))
        if x.ndim < 2 or x.shape[1] % n != 0 or x.shape[0] % max(
            int(np.prod([self.mesh.shape[a] for a in self.batch_axes])), 1
        ) != 0:
            return x
        spec = PartitionSpec(tuple(self.batch_axes), tuple(self.seq_axes),
                             *([None] * (x.ndim - 2)))
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, spec)
        )


def activation_sharding_for(mesh: Mesh, mesh_cfg) -> "ActivationSharding | None":
    """SP/CP activation anchoring implied by the mesh config, or None."""
    if mesh is None or mesh_cfg is None:
        return None
    seq_axes = []
    if mesh.shape.get("context", 1) > 1:
        seq_axes.append("context")
    if (getattr(mesh_cfg, "sequence_parallel", False)
            and mesh.shape.get("tensor", 1) > 1):
        seq_axes.append("tensor")
    if not seq_axes:
        return None
    return ActivationSharding(mesh, tuple(seq_axes),
                              tuple(mesh_cfg.batch_axes))


def batch_pspec(batch_axes: Sequence[str] = ("data", "fsdp")) -> PartitionSpec:
    """PartitionSpec for a batch dim sharded over the given mesh axes.

    Replaces DistributedSampler's rank-strided subsampling *placement*
    (torch:utils/data/distributed.py:134) — each device owns batch rows along
    the flattened (data, fsdp) axes.
    """
    return PartitionSpec(tuple(batch_axes))


def batch_sharding(mesh: Mesh, batch_axes: Sequence[str] = ("data", "fsdp")) -> NamedSharding:
    return NamedSharding(mesh, batch_pspec(batch_axes))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def local_batch_size(global_batch: int, mesh: Mesh, batch_axes=("data", "fsdp")) -> int:
    """Per-host slice of the global batch (SURVEY §3.4 TPU mapping)."""
    n_shards = int(np.prod([mesh.shape[ax] for ax in batch_axes]))
    if global_batch % n_shards != 0:
        raise ValueError(
            f"global batch {global_batch} not divisible by batch shards {n_shards}"
        )
    return global_batch // jax.process_count()
