"""Device-mesh construction.

The reference builds communicator *groups* at runtime
(torch:distributed/distributed_c10d.py:1984 `_new_process_group_helper`,
SURVEY C1/C2); on TPU the analogue is a static ``jax.sharding.Mesh`` whose
named axes ride the ICI torus. One mesh, four axes, unused axes sized 1 —
parallelism strategy becomes pure config (SURVEY §7.2).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

MESH_AXES = ("stage", "data", "fsdp", "expert", "tensor", "context")


def mesh_shape_from_config(mesh_cfg, n_devices: int | None = None) -> dict[str, int]:
    """Resolve axis sizes, expanding a single ``-1`` to fill the device count.

    Mirrors the ergonomics of torchrun's ``--nproc-per-node=auto``
    (torch:distributed/run.py:985): the common case is "use everything".
    """
    if n_devices is None:
        n_devices = jax.device_count()
    sizes = {ax: getattr(mesh_cfg, ax) for ax in MESH_AXES}
    wild = [ax for ax, s in sizes.items() if s == -1]
    if len(wild) > 1:
        raise ValueError(f"at most one mesh axis may be -1, got {wild}")
    fixed = int(np.prod([s for s in sizes.values() if s != -1]))
    if wild:
        if n_devices % fixed != 0:
            raise ValueError(
                f"device count {n_devices} not divisible by fixed axes {sizes}"
            )
        sizes[wild[0]] = n_devices // fixed
    total = int(np.prod(list(sizes.values())))
    if total != n_devices:
        raise ValueError(
            f"mesh {sizes} needs {total} devices but {n_devices} are available"
        )
    return sizes


def build_mesh(mesh_cfg=None, devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Build the global mesh.

    Axis order matters for ICI locality: ``stage`` outermost (pipeline P2P is
    the most DCN-tolerant traffic pattern of all the parallelisms), then
    ``data`` (cross-slice tolerant — gradient all-reduce is latency-tolerant),
    ``tensor``/``context`` innermost (latency-critical per-layer collectives
    ride neighbor ICI links). This is the layout recipe from the scaling-book
    mental model.
    """
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices)
    if mesh_cfg is None:
        sizes = {ax: 1 for ax in MESH_AXES}
        sizes["data"] = devices.size
    else:
        sizes = mesh_shape_from_config(mesh_cfg, devices.size)
    shape = tuple(sizes[ax] for ax in MESH_AXES)
    return Mesh(devices.reshape(shape), MESH_AXES)


@dataclasses.dataclass(frozen=True)
class ActivationSharding:
    """Sequence-dim activation anchoring for SP / CP (SURVEY §2.3 SP row).

    Megatron-style sequence parallelism shards the activations BETWEEN
    tensor-parallel matmuls (norms, residuals, dropout) along the sequence
    dim; re-entering a TP matmul then costs an all-gather and leaving it a
    reduce-scatter — exactly the Megatron SP communication pattern, except
    GSPMD inserts the collectives from these constraints instead of the
    module rewrites torch uses (torch:distributed/tensor/parallel/style.py
    SequenceParallel). ``seq_axes`` may combine 'context' (ring/Ulysses CP)
    with 'tensor' (SP): the sequence dim then shards over both.
    """

    mesh: Mesh
    seq_axes: tuple[str, ...]
    batch_axes: tuple[str, ...] = ("data", "fsdp")

    def constrain(self, x):
        """Anchor (B, S, ...) activations; no-op when S can't divide."""
        import jax

        n = int(np.prod([self.mesh.shape[a] for a in self.seq_axes]))
        if x.ndim < 2 or x.shape[1] % n != 0 or x.shape[0] % max(
            int(np.prod([self.mesh.shape[a] for a in self.batch_axes])), 1
        ) != 0:
            return x
        spec = PartitionSpec(tuple(self.batch_axes), tuple(self.seq_axes),
                             *([None] * (x.ndim - 2)))
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, spec)
        )


def activation_sharding_for(mesh: Mesh, mesh_cfg) -> "ActivationSharding | None":
    """SP/CP activation anchoring implied by the mesh config, or None."""
    if mesh is None or mesh_cfg is None:
        return None
    seq_axes = []
    if mesh.shape.get("context", 1) > 1:
        seq_axes.append("context")
    if (getattr(mesh_cfg, "sequence_parallel", False)
            and mesh.shape.get("tensor", 1) > 1):
        seq_axes.append("tensor")
    if not seq_axes:
        return None
    return ActivationSharding(mesh, tuple(seq_axes),
                              tuple(mesh_cfg.batch_axes))


def batch_pspec(batch_axes: Sequence[str] = ("data", "fsdp")) -> PartitionSpec:
    """PartitionSpec for a batch dim sharded over the given mesh axes.

    Replaces DistributedSampler's rank-strided subsampling *placement*
    (torch:utils/data/distributed.py:134) — each device owns batch rows along
    the flattened (data, fsdp) axes.
    """
    return PartitionSpec(tuple(batch_axes))


def batch_sharding(mesh: Mesh, batch_axes: Sequence[str] = ("data", "fsdp")) -> NamedSharding:
    return NamedSharding(mesh, batch_pspec(batch_axes))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def local_batch_size(global_batch: int, mesh: Mesh, batch_axes=("data", "fsdp")) -> int:
    """Per-host slice of the global batch (SURVEY §3.4 TPU mapping)."""
    n_shards = int(np.prod([mesh.shape[ax] for ax in batch_axes]))
    if global_batch % n_shards != 0:
        raise ValueError(
            f"global batch {global_batch} not divisible by batch shards {n_shards}"
        )
    return global_batch // jax.process_count()
