"""Parallelism = mesh axes + sharding annotations (SURVEY.md §2.3, §7.2).

Replaces the reference stack's four separate wrapper families — DDP
(torch:nn/parallel/distributed.py:466), FSDP
(torch:distributed/fsdp/fully_sharded_data_parallel.py:118), tensor-parallel
styles, and experimental context parallelism — with one
``jax.sharding.Mesh`` over axes ``('data', 'fsdp', 'tensor', 'context')``
plus regex partition rules. XLA's GSPMD partitioner inserts the collectives
the reference issued by hand through c10d.
"""

from pytorch_distributed_train_tpu.parallel.mesh import (  # noqa: F401
    MESH_AXES,
    batch_pspec,
    build_mesh,
    mesh_shape_from_config,
)
from pytorch_distributed_train_tpu.parallel.partition import (  # noqa: F401
    PartitionRules,
    match_partition_rules,
    rules_for_model,
)
