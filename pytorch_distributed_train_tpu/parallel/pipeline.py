"""Pipeline parallelism: SPMD microbatch pipelining over the 'stage' mesh axis.

The TPU-native replacement for torch's pipelining stack
(torch:distributed/pipelining/{stage.py,schedules.py,microbatch.py} — GPipe /
1F1B / Interleaved schedules, SURVEY §2.3 PP row). The torch design is
runtime machinery: per-stage worker processes exchange activations through
P2P sends driven by a schedule interpreter. Here the whole pipeline is ONE
SPMD program: every device runs the same compiled loop, stage identity is
`lax.axis_index('stage')`, and activations hop stage→stage via
`lax.ppermute` on neighbor ICI links (or DCN across slices — PP's
point-to-point pattern is the most DCN-tolerant of all the parallelisms,
which is why 'stage' is the outermost mesh axis).

Schedules:
- ``gpipe`` — all M microbatch forwards, then all backwards (autodiff of the
  scan). Residuals for all T ticks stay live: O(M) activation memory, like
  torch's ``ScheduleGPipe``.
- ``1f1b`` — same compiled forward order, but each tick is wrapped in
  `jax.checkpoint`: the backward re-runs one tick at a time, interleaving
  per-tick recompute+grad exactly where 1F1B interleaves B with F. Live
  activation footprint drops to O(1) ticks (+ the microbatch streams),
  matching ``Schedule1F1B``'s memory motivation. The bubble fraction
  (S-1)/(M+S-1) is identical — it is set by the dependency structure, not
  the runtime.
- ``interleaved`` — circular/interleaved pipelining (torch's
  ``ScheduleInterleavedF1B``): each device holds C CHUNKS of layers
  assigned round-robin over virtual stages (device s owns v ≡ s mod S,
  stored as a (C, S, layers/V) stack sharded on dim 1), and every
  microbatch makes C laps around the ring. The schedule is DENSE across
  the whole batch: at most S microbatches in flight (one per start-tick
  residue class), and a residue class frees exactly when the next
  group's microbatch wants to inject, so all M microbatches pack into
  M·C + S - 1 ticks with only S - 1 bubble ticks of 1/C-sized work —
  the torch steady state (the r3 implementation drained S-1 ticks
  between every group of S). Requires M % S == 0 and
  num_layers % (S·C) == 0.

The loop is differentiable end-to-end (ppermute transposes to the reverse
rotation; psum transposes to a broadcast), so `jax.grad` of a loss on the
pipeline output produces the correct reverse-pipeline backward — there is no
hand-written backward schedule to maintain.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from pytorch_distributed_train_tpu.utils.compat import shard_map

P = PartitionSpec


def _constrain_microbatch(x_mb, mesh: Mesh,
                          batch_axes=("data", "fsdp"),
                          outbound: bool = False) -> jax.Array:
    """Keep GSPMD from leaving batch-sharding on the microbatch-INDEX dim.

    ``microbatch()``'s reshape (B, ...) → (M, mb, ...) makes the sharded
    batch dim split as (M, mb) with the sharding propagating onto M (the
    scanned dim) — and GSPMD cannot move sharding BETWEEN dims in one hop:
    it falls back to replicate-then-repartition with a loud
    spmd_partitioner.cc "Involuntary full rematerialization" warning
    (observed in MULTICHIP_r02), and the same fallback fires inside the
    shard_map entry every step. The dim-move is staged here as two
    transitions the partitioner IS efficient at:
      1. constrain to fully-replicated — one all-gather over the batch
         axes (the same bytes the silent fallback already moved, now as a
         first-class collective);
      2. constrain to the target layout — mb over whatever batch axes
         divide it, M unsharded — a local slice, free.
    The scan body then finds its input already laid out the way it wants
    (per-tick microbatches sharded over data), so no further cross-dim
    moves exist anywhere in the pipeline program.

    The OUTPUT needs the mirror treatment (``outbound=True``): the
    cotangent flowing back from the downstream ``unmicrobatch`` reshape
    arrives batch-sharded on the scanned dim, and the transpose of a
    sharding constraint is the same constraint — so the staged pair runs
    gather→slice in the backward exactly as the inbound pair does in the
    forward.
    """
    mb = x_mb.shape[1]
    chosen: list[str] = []
    prod = 1
    for a in batch_axes:
        n = mesh.shape.get(a, 1)
        if n > 1 and mb % (prod * n) == 0:
            chosen.append(a)
            prod *= n
    replicated = NamedSharding(mesh, P(*([None] * x_mb.ndim)))
    target = NamedSharding(
        mesh, P(None, tuple(chosen) if chosen else None,
                *([None] * (x_mb.ndim - 2))))
    if outbound:
        x_mb = jax.lax.with_sharding_constraint(x_mb, target)
        return jax.lax.with_sharding_constraint(x_mb, replicated)
    x_mb = jax.lax.with_sharding_constraint(x_mb, replicated)
    return jax.lax.with_sharding_constraint(x_mb, target)


def num_stages(mesh: Mesh, stage_axis: str = "stage") -> int:
    return mesh.shape.get(stage_axis, 1)


def spmd_pipeline(
    stage_fn: Callable,
    stage_params: Any,
    x_mb: jax.Array,
    *,
    mesh: Mesh,
    stage_axis: str = "stage",
    schedule: str = "gpipe",
    with_aux: bool = False,
):
    """Run ``stage_fn`` as an S-stage GPipe/1F1B pipeline over microbatches.

    Args:
      stage_fn: ``(local_params, h) -> h`` — applies ONE stage's layers to a
        microbatch of activations. Called inside the manual region; sees its
        stage's shard of ``stage_params`` (leading layer dim divided by S).
        With ``with_aux=True`` it must return ``(h, aux_scalar)`` — e.g. MoE
        load-balance losses sown by the stage's blocks.
      stage_params: pytree whose leaves carry a leading stacked-layer dim
        divisible by the stage count; sharded ``P('stage')`` on that dim.
      x_mb: (M, mb, ...) microbatched activations, replicated over 'stage'
        (other mesh axes — batch/tensor sharding — remain under GSPMD).
      schedule: 'gpipe' | '1f1b' (see module docstring).

    Returns (M, mb, ...) outputs of the final stage, replicated over
    'stage'; with ``with_aux`` returns ``(outputs, aux)`` where aux is the
    MEAN over microbatches of the summed per-stage aux (matching the
    unpipelined model, whose MoE aux is computed once over the full batch).
    """
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    S = num_stages(mesh, stage_axis)
    if S == 1:
        return _sequential(stage_fn, stage_params, x_mb, with_aux)
    M = x_mb.shape[0]
    perm = [(i, (i + 1) % S) for i in range(S)]

    def run(params_local, xs):
        idx = jax.lax.axis_index(stage_axis)

        def tick(state, inputs):
            t, x_t = inputs
            # Stage 0 injects the next microbatch; others consume the
            # activation their neighbor pushed last tick.
            inp = jnp.where(idx == 0, x_t, state)
            if with_aux:
                out, aux = stage_fn(params_local, inp)
                # Bubble ticks run on zero activations — their aux is
                # garbage. Stage s does real work only at ticks [s, s+M).
                real = ((t >= idx) & (t < idx + M)).astype(jnp.float32)
                aux = aux * real
            else:
                out = stage_fn(params_local, inp)
                aux = jnp.float32(0.0)
            nxt = jax.lax.ppermute(out, stage_axis, perm)
            return nxt, (out, aux)

        if schedule == "1f1b":
            tick = jax.checkpoint(tick)

        # T = M + S - 1 ticks: S-1 fill/drain bubble ticks padded with zeros.
        T = M + S - 1
        pad = jnp.zeros((S - 1,) + xs.shape[1:], xs.dtype)
        stream = jnp.concatenate([xs, pad], axis=0)
        state0 = jnp.zeros(xs.shape[1:], xs.dtype)
        _, (ys, auxs) = jax.lax.scan(tick, state0, (jnp.arange(T), stream))

        # Microbatch m finishes on the last stage at tick m + S - 1.
        ys_valid = ys[S - 1:]
        is_last = (idx == S - 1).astype(ys_valid.dtype)
        # Masked psum ≡ broadcast-from-last-stage (transposes to a cheap
        # mask in backward). Communicates one activation tensor per
        # microbatch — the same bytes the torch runtime's final-stage
        # gather moves.
        out = jax.lax.psum(ys_valid * is_last, stage_axis)
        aux = jax.lax.psum(jnp.sum(auxs), stage_axis) / M
        return out, aux

    param_specs = jax.tree.map(lambda _: P(stage_axis), stage_params)
    x_mb = _constrain_microbatch(x_mb, mesh)
    out, aux = shard_map(
        run,
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=(P(), P()),
        axis_names=frozenset({stage_axis}),
        check_vma=False,
    )(stage_params, x_mb)
    out = _constrain_microbatch(out, mesh, outbound=True)
    return (out, aux) if with_aux else out


def _sequential(stage_fn, stage_params, x_mb, with_aux):
    """S=1 degenerate case: one 'stage' holding every layer, no mesh comm."""
    if not with_aux:
        return jax.vmap(lambda x: stage_fn(stage_params, x))(x_mb)
    ys, auxs = jax.vmap(lambda x: stage_fn(stage_params, x))(x_mb)
    return ys, jnp.mean(auxs)


def spmd_pipeline_interleaved(
    stage_fn: Callable,
    chunk_params: Any,
    x_mb: jax.Array,
    *,
    mesh: Mesh,
    stage_axis: str = "stage",
    with_aux: bool = False,
):
    """Circular/interleaved pipeline (see module docstring).

    Args:
      stage_fn: ``(one_chunk_params, h) -> h`` (or ``(h, aux)`` with
        ``with_aux``) applying ONE chunk (layers/V layers) to a microbatch.
      chunk_params: pytree with leading dims (C, S, ...): entry (c, s) is
        virtual stage v = c·S + s. Dim 1 sharded ``P(None, 'stage')``.
      x_mb: (M, mb, ...) microbatches, M % S == 0.

    Returns (M, mb, ...) final-stage outputs (+ mean aux with ``with_aux``),
    replicated over 'stage'.
    """
    S = num_stages(mesh, stage_axis)
    C = jax.tree_util.tree_leaves(chunk_params)[0].shape[0]
    M = x_mb.shape[0]
    if S == 1:
        def seq_fn(params_cs, h):
            aux_total = jnp.float32(0.0)
            for c in range(C):
                p_c = jax.tree.map(lambda a, c=c: a[c, 0], params_cs)
                if with_aux:
                    h, a = stage_fn(p_c, h)
                    aux_total = aux_total + a
                else:
                    h = stage_fn(p_c, h)
            return (h, aux_total) if with_aux else h
        return _sequential(seq_fn, chunk_params, x_mb, with_aux)
    if M % S != 0:
        raise ValueError(f"interleaved schedule needs microbatches {M} "
                         f"divisible by stages {S}")
    V = C * S
    G = M // S
    perm = [(i, (i + 1) % S) for i in range(S)]

    def run(params_local, xs):
        # params_local: (C, 1, ...) — this device's chunks c·S + s.
        params_local = jax.tree.map(lambda a: a[:, 0], params_local)
        idx = jax.lax.axis_index(stage_axis)

        # DENSE schedule (r4, VERDICT r3 weak #5): one scan over ALL
        # groups. Microbatch m = g·S + ρ starts its first chunk at tick
        # τ_m = g·V + ρ; at tick t it sits at virtual stage v = t - τ_m
        # on device v mod S. A residue class ρ is occupied for exactly V
        # consecutive ticks and frees at tick τ_m + V — precisely when
        # the NEXT group's ρ-microbatch wants to inject, so successive
        # groups pack with ZERO gap: total ticks M·C + S - 1 (bubble
        # S - 1, the torch ScheduleInterleaved steady state) instead of
        # the per-group version's M·C + (M/S)·(S - 1).
        T = G * V + S - 1

        def tick(state, t):
            # Device s at tick t: residue ρ = (t - s) mod S identifies
            # the in-flight slot; group g and virtual stage v follow.
            rho = jnp.mod(t - idx, S)
            g = (t - rho) // V
            v = jnp.mod(t - rho, V)
            c = v // S
            m = g * S + rho  # global microbatch index in this slot
            valid = (g >= 0) & (g < G) & (t - rho >= 0)
            # v == 0 on device 0 is an injection tick: the arriving state
            # is the PREVIOUS group's finished microbatch of the same
            # residue (its v hit V last tick) — override with the fresh
            # microbatch. Returning laps (v = S, 2S, ...) consume state.
            inject = (idx == 0) & (v == 0) & valid
            inp = jnp.where(inject, xs[jnp.clip(m, 0, M - 1)], state)
            p_c = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, jnp.clip(c, 0, C - 1), 0, keepdims=False),
                params_local,
            )
            if with_aux:
                out, aux = stage_fn(p_c, inp)
                aux = aux * valid.astype(jnp.float32)
            else:
                out = stage_fn(p_c, inp)
                aux = jnp.float32(0.0)
            # Bubble ticks pass their input through unchanged — keeps
            # garbage zeros from compounding; outputs are only read at
            # valid final-stage ticks anyway.
            out = jnp.where(valid, out, inp)
            nxt = jax.lax.ppermute(out, stage_axis, perm)
            return nxt, (out, aux)

        state0 = jnp.zeros(xs.shape[1:], xs.dtype)
        _, (ys, auxs) = jax.lax.scan(tick, state0, jnp.arange(T))
        # Microbatch m = g·S + ρ finishes (v = V-1, device S-1) at tick
        # τ_m + V - 1 = g·V + ρ + V - 1 — a static gather per microbatch.
        is_last = (idx == S - 1).astype(ys.dtype)
        ys = jax.lax.psum(ys * is_last, stage_axis)
        t_of_m = jnp.asarray(
            [(m // S) * V + (m % S) + V - 1 for m in range(M)])
        out = jnp.take(ys, t_of_m, axis=0)
        total_aux = jax.lax.psum(jnp.sum(auxs), stage_axis) / M
        return out, total_aux

    param_specs = jax.tree.map(lambda _: P(None, stage_axis), chunk_params)
    x_mb = _constrain_microbatch(x_mb, mesh)
    out, aux = shard_map(
        run,
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=(P(), P()),
        axis_names=frozenset({stage_axis}),
        check_vma=False,
    )(chunk_params, x_mb)
    out = _constrain_microbatch(out, mesh, outbound=True)
    return (out, aux) if with_aux else out


def microbatch(x: jax.Array, num_microbatches: int) -> jax.Array:
    """(B, ...) → (M, B/M, ...). The analogue of torch's
    pipelining/microbatch.py split; static shapes required under jit."""
    B = x.shape[0]
    if B % num_microbatches != 0:
        raise ValueError(
            f"batch {B} not divisible by {num_microbatches} microbatches"
        )
    return x.reshape((num_microbatches, B // num_microbatches) + x.shape[1:])


def unmicrobatch(x_mb: jax.Array) -> jax.Array:
    """(M, mb, ...) → (M·mb, ...)."""
    return x_mb.reshape((x_mb.shape[0] * x_mb.shape[1],) + x_mb.shape[2:])
