"""Pipeline parallelism: SPMD microbatch pipelining over the 'stage' mesh axis.

The TPU-native replacement for torch's pipelining stack
(torch:distributed/pipelining/{stage.py,schedules.py,microbatch.py} — GPipe /
1F1B / Interleaved schedules, SURVEY §2.3 PP row). The torch design is
runtime machinery: per-stage worker processes exchange activations through
P2P sends driven by a schedule interpreter. Here the whole pipeline is ONE
SPMD program: every device runs the same compiled loop, stage identity is
`lax.axis_index('stage')`, and activations hop stage→stage via
`lax.ppermute` on neighbor ICI links (or DCN across slices — PP's
point-to-point pattern is the most DCN-tolerant of all the parallelisms,
which is why 'stage' is the outermost mesh axis).

Schedules:
- ``gpipe`` — all M microbatch forwards, then all backwards (autodiff of the
  scan). Residuals for all T ticks stay live: O(M) activation memory, like
  torch's ``ScheduleGPipe``.
- ``1f1b`` — same compiled forward order, but each tick is wrapped in
  `jax.checkpoint`: the backward re-runs one tick at a time, interleaving
  per-tick recompute+grad exactly where 1F1B interleaves B with F. Live
  activation footprint drops to O(1) ticks (+ the microbatch streams),
  matching ``Schedule1F1B``'s memory motivation. The bubble fraction
  (S-1)/(M+S-1) is identical — it is set by the dependency structure, not
  the runtime.

The loop is differentiable end-to-end (ppermute transposes to the reverse
rotation; psum transposes to a broadcast), so `jax.grad` of a loss on the
pipeline output produces the correct reverse-pipeline backward — there is no
hand-written backward schedule to maintain.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

P = PartitionSpec


def num_stages(mesh: Mesh, stage_axis: str = "stage") -> int:
    return mesh.shape.get(stage_axis, 1)


def spmd_pipeline(
    stage_fn: Callable,
    stage_params: Any,
    x_mb: jax.Array,
    *,
    mesh: Mesh,
    stage_axis: str = "stage",
    schedule: str = "gpipe",
    with_aux: bool = False,
):
    """Run ``stage_fn`` as an S-stage GPipe/1F1B pipeline over microbatches.

    Args:
      stage_fn: ``(local_params, h) -> h`` — applies ONE stage's layers to a
        microbatch of activations. Called inside the manual region; sees its
        stage's shard of ``stage_params`` (leading layer dim divided by S).
        With ``with_aux=True`` it must return ``(h, aux_scalar)`` — e.g. MoE
        load-balance losses sown by the stage's blocks.
      stage_params: pytree whose leaves carry a leading stacked-layer dim
        divisible by the stage count; sharded ``P('stage')`` on that dim.
      x_mb: (M, mb, ...) microbatched activations, replicated over 'stage'
        (other mesh axes — batch/tensor sharding — remain under GSPMD).
      schedule: 'gpipe' | '1f1b' (see module docstring).

    Returns (M, mb, ...) outputs of the final stage, replicated over
    'stage'; with ``with_aux`` returns ``(outputs, aux)`` where aux is the
    MEAN over microbatches of the summed per-stage aux (matching the
    unpipelined model, whose MoE aux is computed once over the full batch).
    """
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    S = num_stages(mesh, stage_axis)
    if S == 1:
        return _sequential(stage_fn, stage_params, x_mb, with_aux)
    M = x_mb.shape[0]
    perm = [(i, (i + 1) % S) for i in range(S)]

    def run(params_local, xs):
        idx = jax.lax.axis_index(stage_axis)

        def tick(state, inputs):
            t, x_t = inputs
            # Stage 0 injects the next microbatch; others consume the
            # activation their neighbor pushed last tick.
            inp = jnp.where(idx == 0, x_t, state)
            if with_aux:
                out, aux = stage_fn(params_local, inp)
                # Bubble ticks run on zero activations — their aux is
                # garbage. Stage s does real work only at ticks [s, s+M).
                real = ((t >= idx) & (t < idx + M)).astype(jnp.float32)
                aux = aux * real
            else:
                out = stage_fn(params_local, inp)
                aux = jnp.float32(0.0)
            nxt = jax.lax.ppermute(out, stage_axis, perm)
            return nxt, (out, aux)

        if schedule == "1f1b":
            tick = jax.checkpoint(tick)

        # T = M + S - 1 ticks: S-1 fill/drain bubble ticks padded with zeros.
        T = M + S - 1
        pad = jnp.zeros((S - 1,) + xs.shape[1:], xs.dtype)
        stream = jnp.concatenate([xs, pad], axis=0)
        state0 = jnp.zeros(xs.shape[1:], xs.dtype)
        _, (ys, auxs) = jax.lax.scan(tick, state0, (jnp.arange(T), stream))

        # Microbatch m finishes on the last stage at tick m + S - 1.
        ys_valid = ys[S - 1:]
        is_last = (idx == S - 1).astype(ys_valid.dtype)
        # Masked psum ≡ broadcast-from-last-stage (transposes to a cheap
        # mask in backward). Communicates one activation tensor per
        # microbatch — the same bytes the torch runtime's final-stage
        # gather moves.
        out = jax.lax.psum(ys_valid * is_last, stage_axis)
        aux = jax.lax.psum(jnp.sum(auxs), stage_axis) / M
        return out, aux

    param_specs = jax.tree.map(lambda _: P(stage_axis), stage_params)
    out, aux = jax.shard_map(
        run,
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=(P(), P()),
        axis_names=frozenset({stage_axis}),
        check_vma=False,
    )(stage_params, x_mb)
    return (out, aux) if with_aux else out


def _sequential(stage_fn, stage_params, x_mb, with_aux):
    """S=1 degenerate case: one 'stage' holding every layer, no mesh comm."""
    if not with_aux:
        return jax.vmap(lambda x: stage_fn(stage_params, x))(x_mb)
    ys, auxs = jax.vmap(lambda x: stage_fn(stage_params, x))(x_mb)
    return ys, jnp.mean(auxs)


def microbatch(x: jax.Array, num_microbatches: int) -> jax.Array:
    """(B, ...) → (M, B/M, ...). The analogue of torch's
    pipelining/microbatch.py split; static shapes required under jit."""
    B = x.shape[0]
    if B % num_microbatches != 0:
        raise ValueError(
            f"batch {B} not divisible by {num_microbatches} microbatches"
        )
    return x.reshape((num_microbatches, B // num_microbatches) + x.shape[1:])


def unmicrobatch(x_mb: jax.Array) -> jax.Array:
    """(M, mb, ...) → (M·mb, ...)."""
    return x_mb.reshape((x_mb.shape[0] * x_mb.shape[1],) + x_mb.shape[2:])
