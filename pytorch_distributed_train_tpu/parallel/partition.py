"""Regex partition rules: param-name path → PartitionSpec.

The TPU-native replacement for FSDP's FlatParameter sharding
(torch:distributed/fsdp/_flat_param.py:202) and tensor-parallel module styles
(torch:distributed/tensor/parallel/style.py): instead of wrapping modules,
we map each parameter's pytree path through an ordered list of
``(regex, PartitionSpec)`` rules (the GSPMD idiom — SURVEY C13, SNIPPETS §[2]
pattern). XLA then inserts the all-gathers / reduce-scatters that FSDP's
runtime performed by hand.

Rules are matched against '/'-joined flax param paths, e.g.
``params/encoder/layers_3/attn/q_proj/kernel``. First match wins; scalars are
always replicated; a catch-all ``.*`` rule should end every rule set.
"""

from __future__ import annotations

import re
from typing import Any, Callable

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec


class PartitionRules:
    """Ordered (regex, PartitionSpec) table applied to a params pytree."""

    def __init__(self, rules: list[tuple[str, PartitionSpec]]):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_for(self, name: str, shape: tuple[int, ...]) -> PartitionSpec:
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()
        for pat, spec in self.rules:
            if pat.search(name):
                return spec
        raise ValueError(f"no partition rule matched param {name!r} (shape {shape})")

    def tree_specs(self, params: Any) -> Any:
        """Pytree of PartitionSpec matching ``params``' structure."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        specs = [
            self.spec_for(path_name(p), getattr(leaf, "shape", ()))
            for p, leaf in flat
        ]
        return jax.tree_util.tree_unflatten(treedef, specs)

    def tree_shardings(self, mesh: Mesh, params: Any) -> Any:
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        shardings = []
        for p, leaf in flat:
            shape = getattr(leaf, "shape", ())
            spec = self.spec_for(path_name(p), shape)
            spec = validate_spec(spec, shape, mesh)
            shardings.append(NamedSharding(mesh, spec))
        return jax.tree_util.tree_unflatten(treedef, shardings)


def validate_spec(spec: PartitionSpec, shape: tuple[int, ...], mesh: Mesh
                  ) -> PartitionSpec:
    """Drop sharding on dims the mesh can't divide evenly.

    GSPMD requires dim % (product of assigned axis sizes) == 0; real models
    always have stray dims (num_classes=10, vocab remainders) that a generic
    rule can't shard on every mesh — fall back to replicating THAT dim only,
    which is exactly what FSDP's pad-to-divisible flat-param avoids at the
    cost of padding (we prefer replication: these dims are small).
    Also truncates specs longer than the array rank (a 2-d rule matched
    against a reshaped scalar etc.)."""
    entries = list(spec)
    out = []
    for i, entry in enumerate(entries[: len(shape)]):
        if entry is None:
            out.append(None)
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        size = int(np.prod([mesh.shape[a] for a in axes]))
        out.append(entry if shape[i] % size == 0 else None)
    return PartitionSpec(*out)


def replication_fallback_dims(spec: PartitionSpec, shape: tuple[int, ...],
                              sizes: dict[str, int]) -> list[int]:
    """Dims of ``shape`` that a mesh with the given axis sizes could NOT
    shard as ``spec`` asks — ``validate_spec`` would replicate them.

    The dict-of-sizes twin of ``validate_spec``: the elastic-reshard
    feasibility question ("can this checkpoint restore onto mesh X?",
    tools/ckpt_inspect.py --mesh) must be answerable WITHOUT
    constructing a jax Mesh, whose device grid needs the target
    machine's actual devices."""
    out = []
    for i, entry in enumerate(list(spec)[: len(shape)]):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        size = int(np.prod([sizes.get(a, 1) for a in axes]))
        if size > 1 and shape[i] % size != 0:
            out.append(i)
    return out


def path_name(path) -> str:
    """'/'-joined readable name for a jax key path."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


def match_partition_rules(rules: list[tuple[str, PartitionSpec]], params: Any) -> Any:
    """Functional one-shot form (SNIPPETS §[2] pattern, reimplemented)."""
    return PartitionRules(rules).tree_specs(params)


def grad_buckets(params: Any, bucket_bytes: int) -> list[list[int]]:
    """Gradient buckets for the overlapped-collectives path
    (steps.overlap_grad_reducer) — the layout half of DDP's reducer
    (torch reducer.hpp:285 / ``bucket_cap_mb``).

    Flattened-leaf indices grouped in REVERSE parameter order (backward
    produces grads output-end first, so the last layers' buckets close —
    and their collectives launch — while earlier layers still compute),
    each bucket closing once its cumulative byte size reaches
    ``bucket_bytes``. Works on arrays or ShapeDtypeStructs (AOT
    bucketing from an eval_shape tree, no materialized params needed).
    Invariants the tests pin: every leaf appears in exactly one bucket;
    concatenating the buckets yields exactly ``reversed(range(n))``;
    every bucket except possibly the last meets the byte floor."""
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be > 0, got {bucket_bytes}")
    leaves = jax.tree_util.tree_leaves(params)
    buckets: list[list[int]] = []
    cur: list[int] = []
    size = 0
    for i in reversed(range(len(leaves))):
        leaf = leaves[i]
        cur.append(i)
        size += int(np.prod(getattr(leaf, "shape", ()) or (1,))) * \
            np.dtype(leaf.dtype).itemsize
        if size >= bucket_bytes:
            buckets.append(cur)
            cur, size = [], 0
    if cur:
        buckets.append(cur)
    return buckets


# ------------------------------------------------------------------ rule sets
#
# Sharding recipes per model family. Convention on axis use:
#   'fsdp'   — ZeRO-style weight sharding; shard the LARGEST dim that is not
#              already tensor-sharded, so reshards are cheap.
#   'tensor' — megatron TP: column-parallel on q/k/v/up projections
#              (output dim), row-parallel on o/down projections (input dim).
# Biases/norm scales replicate. The optimizer state inherits these specs
# through jit's sharding propagation (SURVEY C13 rightmost column).


def dense_rules() -> list[tuple[str, PartitionSpec]]:
    """Fallback for unregistered models: shard kernels on their output
    channel (conv HWIO dim 3; dense (in,out) dim 1) over 'fsdp'; replicate
    the rest. Conv rule must precede the generic kernel rule — regex can't
    see array rank."""
    return [
        (r"conv[^/]*/kernel$", P(None, None, None, "fsdp")),
        (r"(kernel|embedding)$", P(None, "fsdp")),
        (r".*", P()),
    ]


def llama_rules() -> list[tuple[str, PartitionSpec]]:
    """Llama-2: FSDP × TP layout (BASELINE.json:11).

    Matches flax param paths from models/llama.py.
    """
    return [
        # Embedding: vocab × hidden — VOCAB over 'fsdp', hidden unsharded.
        # This is the GSPMD-friendly gather layout: a vocab-sharded table
        # lowers to masked-gather + psum over 'fsdp' and the output
        # inherits the token indices' (batch, seq) sharding directly.
        # Sharding hidden instead (or vocab over 'tensor', the tied-weight
        # layout) left the partitioner resharding the gather output via
        # "involuntary full rematerialization" under tp×cp meshes
        # (observed in dryrun_multichip).
        (r"tok_embed/embedding$", P("fsdp", None)),
        # Attention: hidden × (heads·head_dim)
        (r"(q_proj|k_proj|v_proj)/kernel$", P("fsdp", "tensor")),
        (r"o_proj/kernel$", P("tensor", "fsdp")),
        # MoE experts (leading E dim over 'expert'); router replicated.
        # Must precede the dense-MLP rules — same projection names.
        (r"experts/(gate_proj|up_proj)/kernel$", P("expert", "fsdp", "tensor")),
        (r"experts/down_proj/kernel$", P("expert", "tensor", "fsdp")),
        (r"router/kernel$", P()),
        # MLP: gate/up column-parallel, down row-parallel
        (r"(gate_proj|up_proj)/kernel$", P("fsdp", "tensor")),
        (r"down_proj/kernel$", P("tensor", "fsdp")),
        # Final LM head
        (r"lm_head/kernel$", P("fsdp", "tensor")),
        # Norm scales replicate
        (r"(input_norm|post_attn_norm|final_norm)/scale$", P()),
        (r".*", P()),
    ]


def llama_pp_rules() -> list[tuple[str, PartitionSpec]]:
    """Pipelined Llama (models/pipeline_lm.py): block params carry a leading
    stacked-layer dim sharded over 'stage'; within a layer the FSDP×TP layout
    matches llama_rules. Embed/head live outside the pipeline (replicated
    over 'stage', sharded over fsdp/tensor as usual)."""
    return [
        # Interleaved-schedule storage (C, S, Lps, ...): stage on dim 1.
        (r"blocks_csl/.*(q_proj|k_proj|v_proj)/kernel$",
         P(None, "stage", None, "fsdp", "tensor")),
        (r"blocks_csl/.*o_proj/kernel$",
         P(None, "stage", None, "tensor", None, "fsdp")),
        (r"blocks_csl/.*experts/(gate_proj|up_proj)/kernel$",
         P(None, "stage", None, "expert", "fsdp", "tensor")),
        (r"blocks_csl/.*experts/down_proj/kernel$",
         P(None, "stage", None, "expert", "tensor", "fsdp")),
        (r"blocks_csl/.*router/kernel$", P(None, "stage")),
        (r"blocks_csl/.*(gate_proj|up_proj)/kernel$",
         P(None, "stage", None, "fsdp", "tensor")),
        (r"blocks_csl/.*down_proj/kernel$",
         P(None, "stage", None, "tensor", "fsdp")),
        (r"blocks_csl/.*scale$", P(None, "stage")),
        # GPipe/1F1B storage (L, ...): stage on dim 0.
        (r"blocks/.*(q_proj|k_proj|v_proj)/kernel$",
         P("stage", "fsdp", "tensor")),
        (r"blocks/.*o_proj/kernel$", P("stage", "tensor", None, "fsdp")),
        # MoE experts: (L, E, ...) — stage on layers, expert on experts.
        # Must precede the dense-MLP rules (same projection names).
        (r"blocks/.*experts/(gate_proj|up_proj)/kernel$",
         P("stage", "expert", "fsdp", "tensor")),
        (r"blocks/.*experts/down_proj/kernel$",
         P("stage", "expert", "tensor", "fsdp")),
        (r"blocks/.*router/kernel$", P("stage")),
        (r"blocks/.*(gate_proj|up_proj)/kernel$", P("stage", "fsdp", "tensor")),
        (r"blocks/.*down_proj/kernel$", P("stage", "tensor", "fsdp")),
        (r"blocks/.*scale$", P("stage")),
        # vocab over 'fsdp' — same gather-friendly layout as llama_rules
        (r"tok_embed/embedding$", P("fsdp", None)),
        (r"lm_head/kernel$", P("fsdp", "tensor")),
        (r".*", P()),
    ]


def gpt2_rules() -> list[tuple[str, PartitionSpec]]:
    """GPT-2: FSDP × TP. Tied head means the vocab-over-'fsdp' embedding is
    also the output projection; the logsumexp then reduces over 'fsdp'."""
    return [
        (r"wte/embedding$", P("fsdp", None)),
        (r"wpe$", P()),
        (r"(q_proj|k_proj|v_proj)/kernel$", P("fsdp", "tensor")),
        (r"attn/c_proj/kernel$", P("tensor", None, "fsdp")),
        (r"c_fc/kernel$", P("fsdp", "tensor")),
        (r"c_proj/kernel$", P("tensor", "fsdp")),
        (r".*", P()),
    ]


def bert_rules() -> list[tuple[str, PartitionSpec]]:
    return [
        (r"(word_embed|pos_embed|type_embed)/embedding$", P(None, "fsdp")),
        (r"(query|key|value)/kernel$", P("fsdp", "tensor")),
        (r"attn_out/kernel$", P("tensor", "fsdp")),
        (r"mlp_in/kernel$", P("fsdp", "tensor")),
        (r"mlp_out/kernel$", P("tensor", "fsdp")),
        (r"(mlm_dense|pooler)/kernel$", P("fsdp", None)),
        (r".*", P()),
    ]


def vit_rules() -> list[tuple[str, PartitionSpec]]:
    return [
        (r"patch_embed/kernel$", P(None, None, None, "fsdp")),
        (r"(query|key|value)/kernel$", P("fsdp", "tensor")),
        (r"attn_out/kernel$", P("tensor", "fsdp")),
        (r"mlp_in/kernel$", P("fsdp", "tensor")),
        (r"mlp_out/kernel$", P("tensor", "fsdp")),
        (r"head/kernel$", P("fsdp", None)),
        (r".*", P()),
    ]


def resnet_rules() -> list[tuple[str, PartitionSpec]]:
    """ResNets are small — replicate params (DDP-equivalent), shard only batch.
    With fsdp>1 conv kernels shard on output channels (HWIO last dim)."""
    return [
        (r"conv[^/]*/kernel$", P(None, None, None, "fsdp")),
        (r"fc/kernel$", P(None, "fsdp")),
        (r".*", P()),
    ]


def t5_rules() -> list[tuple[str, PartitionSpec]]:
    """T5 encoder-decoder (models/t5.py): the llama FSDP×TP recipe applied
    to both stacks — q/k/v column-parallel over 'tensor', o row-parallel,
    MLP wi/wo likewise; the shared embedding vocab-sharded over 'fsdp'
    (same gather-layout rationale as llama's tok_embed rule); relative-
    bias tables and norm scales replicate (tiny)."""
    return [
        (r"shared/embedding$", P("fsdp", None)),
        (r"(q_proj|k_proj|v_proj)/kernel$", P("fsdp", "tensor")),
        (r"o_proj/kernel$", P("tensor", None, "fsdp")),
        (r"mlp/wi/kernel$", P("fsdp", "tensor")),
        (r"mlp/wo/kernel$", P("tensor", "fsdp")),
        (r"lm_head/kernel$", P("fsdp", "tensor")),
        (r"rel_bias/embedding$", P()),
        (r".*", P()),
    ]


_RULE_SETS: dict[str, Callable[[], list[tuple[str, PartitionSpec]]]] = {
    "resnet": resnet_rules,
    "vit": vit_rules,
    "bert": bert_rules,
    "gpt": gpt2_rules,
    "llama_pp": llama_pp_rules,  # must precede the "llama" prefix match
    "llama": llama_rules,
    "t5": t5_rules,
    "dense": dense_rules,
}


def rules_for_model(model_name: str) -> PartitionRules:
    # LoRA adapter leaves (lora.py) replicate: rank-r matrices are tiny
    # (d*r vs d*d), and replication keeps the A@B fold free of collectives
    # inside the merged train step. Prepended so the family rule sets'
    # generic `kernel` patterns can never capture them.
    lora_rules = [(r"lora_[ab]$", P())]
    for prefix, fn in _RULE_SETS.items():
        if model_name.startswith(prefix):
            return PartitionRules(lora_rules + fn())
    return PartitionRules(lora_rules + dense_rules())
