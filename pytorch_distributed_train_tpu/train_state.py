"""Train state: params + opt state + BN stats + step, as one pytree.

The analogue of the reference's {model.state_dict(), optimizer.state_dict(),
epoch} checkpoint triple (SURVEY §3.5) — but a single immutable pytree that
flows through the jitted step with donated buffers. Loss-scale state (the
GradScaler replacement, SURVEY C19) lives here too when enabled.
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp
import optax


def _has_plateau_state(opt_state) -> bool:
    """Whether a reduce_on_plateau state sits anywhere in the tree (its
    leaves duck-type on the plateau_count field)."""
    return any(
        hasattr(s, "plateau_count")
        for s in jax.tree.leaves(
            opt_state, is_leaf=lambda s: hasattr(s, "plateau_count")))


@flax.struct.dataclass
class DynamicScale:
    """Dynamic fp16 loss scaling — optax-style replacement for
    torch.amp.GradScaler (torch:amp/grad_scaler.py:53): scale up every
    `growth_interval` finite steps, halve on overflow, skip the update on
    non-finite grads."""

    scale: jnp.ndarray  # f32 scalar
    growth_tracker: jnp.ndarray  # i32 scalar
    growth_interval: int = flax.struct.field(pytree_node=False, default=2000)

    @classmethod
    def create(cls, init_scale: float, growth_interval: int) -> "DynamicScale":
        return cls(
            scale=jnp.float32(init_scale),
            growth_tracker=jnp.int32(0),
            growth_interval=growth_interval,
        )

    def update(self, grads_finite: jnp.ndarray) -> "DynamicScale":
        grown = self.growth_tracker + 1
        should_grow = grown >= self.growth_interval
        new_scale = jnp.where(
            grads_finite,
            jnp.where(should_grow, self.scale * 2.0, self.scale),
            jnp.maximum(self.scale * 0.5, 1.0),
        )
        new_tracker = jnp.where(
            grads_finite & ~should_grow, grown, jnp.int32(0)
        )
        return self.replace(scale=new_scale, growth_tracker=new_tracker)


@flax.struct.dataclass
class TrainState:
    """Pure-array pytree. The optimizer transform `tx` is deliberately NOT a
    field: function identity in treedef metadata breaks pytree equality
    across rebuilds (e.g. restore-then-step with a freshly constructed
    optimizer) — the step function closes over tx instead."""

    step: jnp.ndarray  # i32 scalar
    params: Any
    opt_state: Any
    batch_stats: Any  # BN running stats ({} for stat-free models)
    dynamic_scale: DynamicScale | None = None
    # Polyak/EMA weight average (the torch-recipe "model EMA"): a params
    # mirror updated ema = d*ema + (1-d)*params each step; None when off.
    # SWA (torch.optim.swa_utils) reuses the SAME mirror with an
    # equal-weight running mean; swa_count is how many snapshots it holds.
    ema_params: Any = None
    swa_count: Any = None  # i32 scalar when SWA is on, else None
    # BN running stats mirrored with the same EMA decay (timm ModelEma
    # semantics): averaged weights shift every layer's input distribution,
    # so evaluating the EMA params against the TRAJECTORY stats silently
    # mis-normalizes (VERDICT r3 weak #5). Non-None exactly when EMA is on
    # AND the model carries batch_stats; SWA keeps this None — its recipe
    # re-estimates stats via trainer.update_bn (torch swa_utils.update_bn).
    ema_batch_stats: Any = None

    def apply_gradients(self, tx: optax.GradientTransformation, grads,
                        new_batch_stats=None, ema_decay: float = 0.0,
                        swa_start: int = 0, swa_every: int = 1,
                        loss=None):
        # reduce_on_plateau in the chain REQUIRES value=; other chains
        # reject the kwarg. Detect the plateau state structurally (trace-
        # time pytree walk, zero runtime cost) so every caller that passes
        # the loss is safe regardless of which OptimConfig built the tx.
        if loss is not None and _has_plateau_state(self.opt_state):
            updates, new_opt_state = tx.update(
                grads, self.opt_state, self.params, value=loss)
        else:
            updates, new_opt_state = tx.update(
                grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        ema = self.ema_params
        swa_count = self.swa_count
        if ema is not None and swa_start > 0:
            # SWA: from the swa_start-th OPTIMIZER UPDATE on (the same
            # denomination as warmup_steps), fold every swa_every-th
            # update's params into the equal-weight running mean
            # avg += (p - avg)/(n+1). Under MultiSteps the update counter
            # is gradient_step, so accumulation cannot alias the stride.
            if isinstance(new_opt_state, optax.MultiStepsState):
                upd = new_opt_state.gradient_step
                boundary = new_opt_state.mini_step == 0
            else:
                upd = self.step + 1
                boundary = jnp.bool_(True)
            take = boundary & (upd >= swa_start) & (
                (upd - swa_start) % swa_every == 0)
            n = swa_count + take.astype(jnp.int32)
            ema = jax.tree.map(
                lambda avg, p: jnp.where(
                    take,
                    avg + (p.astype(avg.dtype) - avg)
                    / jnp.maximum(n, 1).astype(avg.dtype),
                    avg),
                ema, new_params)
            swa_count = n
        ema_stats = self.ema_batch_stats
        if ema is not None and ema_decay > 0.0 and not (swa_start > 0):
            stepped = optax.incremental_update(new_params, ema,
                                               1.0 - ema_decay)
            if isinstance(new_opt_state, optax.MultiStepsState):
                # Under gradient accumulation only the boundary micro-step
                # changes params; decaying on every micro-step would shorten
                # the averaging window by accum_steps. mini_step wraps to 0
                # exactly when the inner optimizer fired.
                boundary = new_opt_state.mini_step == 0
                ema = jax.tree.map(
                    lambda new, old: jnp.where(boundary, new, old),
                    stepped, ema)
            else:
                ema = stepped
            if ema_stats is not None and new_batch_stats is not None:
                # Stats change on EVERY forward (no accumulation boundary
                # gate): the mirror tracks the stats stream the same way
                # the model's own running average does.
                ema_stats = optax.incremental_update(
                    new_batch_stats, ema_stats, 1.0 - ema_decay)
        return self.replace(
            step=self.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            batch_stats=(
                new_batch_stats if new_batch_stats is not None else self.batch_stats
            ),
            ema_params=ema,
            swa_count=swa_count,
            ema_batch_stats=ema_stats,
        )

    @property
    def eval_params(self):
        """What evaluation should run on: the EMA mirror when enabled."""
        return self.ema_params if self.ema_params is not None else self.params

    @property
    def eval_batch_stats(self):
        """BN stats matching eval_params: the EMA stats mirror when it
        exists, else the trajectory stats (stat-free models: {})."""
        return (self.ema_batch_stats if self.ema_batch_stats is not None
                else self.batch_stats)

    @classmethod
    def create(cls, *, params, tx, batch_stats=None, dynamic_scale=None,
               ema: bool = False, swa: bool = False):
        batch_stats = batch_stats if batch_stats is not None else {}
        return cls(
            step=jnp.int32(0),
            params=params,
            opt_state=tx.init(params),
            batch_stats=batch_stats,
            dynamic_scale=dynamic_scale,
            ema_params=params if (ema or swa) else None,
            swa_count=jnp.int32(0) if swa else None,
            # EMA only: SWA re-estimates via update_bn instead (torch
            # swa_utils recipe) and keeps no stats mirror.
            ema_batch_stats=batch_stats if (ema and batch_stats) else None,
        )
