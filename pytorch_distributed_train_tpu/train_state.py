"""Train state: params + opt state + BN stats + step, as one pytree.

The analogue of the reference's {model.state_dict(), optimizer.state_dict(),
epoch} checkpoint triple (SURVEY §3.5) — but a single immutable pytree that
flows through the jitted step with donated buffers. Loss-scale state (the
GradScaler replacement, SURVEY C19) lives here too when enabled.
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp
import optax


def _has_plateau_state(opt_state) -> bool:
    """Whether a reduce_on_plateau state sits anywhere in the tree (its
    leaves duck-type on the plateau_count field)."""
    return any(
        hasattr(s, "plateau_count")
        for s in jax.tree.leaves(
            opt_state, is_leaf=lambda s: hasattr(s, "plateau_count")))


@flax.struct.dataclass
class DynamicScale:
    """Dynamic fp16 loss scaling — optax-style replacement for
    torch.amp.GradScaler (torch:amp/grad_scaler.py:53): scale up every
    `growth_interval` finite steps, halve on overflow, skip the update on
    non-finite grads."""

    scale: jnp.ndarray  # f32 scalar
    growth_tracker: jnp.ndarray  # i32 scalar
    growth_interval: int = flax.struct.field(pytree_node=False, default=2000)

    @classmethod
    def create(cls, init_scale: float, growth_interval: int) -> "DynamicScale":
        return cls(
            scale=jnp.float32(init_scale),
            growth_tracker=jnp.int32(0),
            growth_interval=growth_interval,
        )

    def update(self, grads_finite: jnp.ndarray) -> "DynamicScale":
        grown = self.growth_tracker + 1
        should_grow = grown >= self.growth_interval
        new_scale = jnp.where(
            grads_finite,
            jnp.where(should_grow, self.scale * 2.0, self.scale),
            jnp.maximum(self.scale * 0.5, 1.0),
        )
        new_tracker = jnp.where(
            grads_finite & ~should_grow, grown, jnp.int32(0)
        )
        return self.replace(scale=new_scale, growth_tracker=new_tracker)


@flax.struct.dataclass
class TrainState:
    """Pure-array pytree. The optimizer transform `tx` is deliberately NOT a
    field: function identity in treedef metadata breaks pytree equality
    across rebuilds (e.g. restore-then-step with a freshly constructed
    optimizer) — the step function closes over tx instead."""

    step: jnp.ndarray  # i32 scalar
    params: Any
    opt_state: Any
    batch_stats: Any  # BN running stats ({} for stat-free models)
    dynamic_scale: DynamicScale | None = None
    # Polyak/EMA weight average (the torch-recipe "model EMA"): a params
    # mirror updated ema = d*ema + (1-d)*params each step; None when off.
    # Params only — BN stats are not averaged (matters only for BN models;
    # the classic EMA consumer here is ViT, which has none).
    # SWA (torch.optim.swa_utils) reuses the SAME mirror with an
    # equal-weight running mean; swa_count is how many snapshots it holds.
    ema_params: Any = None
    swa_count: Any = None  # i32 scalar when SWA is on, else None

    def apply_gradients(self, tx: optax.GradientTransformation, grads,
                        new_batch_stats=None, ema_decay: float = 0.0,
                        swa_start: int = 0, swa_every: int = 1,
                        loss=None):
        # reduce_on_plateau in the chain REQUIRES value=; other chains
        # reject the kwarg. Detect the plateau state structurally (trace-
        # time pytree walk, zero runtime cost) so every caller that passes
        # the loss is safe regardless of which OptimConfig built the tx.
        if loss is not None and _has_plateau_state(self.opt_state):
            updates, new_opt_state = tx.update(
                grads, self.opt_state, self.params, value=loss)
        else:
            updates, new_opt_state = tx.update(
                grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        ema = self.ema_params
        swa_count = self.swa_count
        if ema is not None and swa_start > 0:
            # SWA: from the swa_start-th OPTIMIZER UPDATE on (the same
            # denomination as warmup_steps), fold every swa_every-th
            # update's params into the equal-weight running mean
            # avg += (p - avg)/(n+1). Under MultiSteps the update counter
            # is gradient_step, so accumulation cannot alias the stride.
            if isinstance(new_opt_state, optax.MultiStepsState):
                upd = new_opt_state.gradient_step
                boundary = new_opt_state.mini_step == 0
            else:
                upd = self.step + 1
                boundary = jnp.bool_(True)
            take = boundary & (upd >= swa_start) & (
                (upd - swa_start) % swa_every == 0)
            n = swa_count + take.astype(jnp.int32)
            ema = jax.tree.map(
                lambda avg, p: jnp.where(
                    take,
                    avg + (p.astype(avg.dtype) - avg)
                    / jnp.maximum(n, 1).astype(avg.dtype),
                    avg),
                ema, new_params)
            swa_count = n
        elif ema is not None and ema_decay > 0.0:
            stepped = optax.incremental_update(new_params, ema,
                                               1.0 - ema_decay)
            if isinstance(new_opt_state, optax.MultiStepsState):
                # Under gradient accumulation only the boundary micro-step
                # changes params; decaying on every micro-step would shorten
                # the averaging window by accum_steps. mini_step wraps to 0
                # exactly when the inner optimizer fired.
                boundary = new_opt_state.mini_step == 0
                ema = jax.tree.map(
                    lambda new, old: jnp.where(boundary, new, old),
                    stepped, ema)
            else:
                ema = stepped
        return self.replace(
            step=self.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            batch_stats=(
                new_batch_stats if new_batch_stats is not None else self.batch_stats
            ),
            ema_params=ema,
            swa_count=swa_count,
        )

    @property
    def eval_params(self):
        """What evaluation should run on: the EMA mirror when enabled."""
        return self.ema_params if self.ema_params is not None else self.params

    @classmethod
    def create(cls, *, params, tx, batch_stats=None, dynamic_scale=None,
               ema: bool = False, swa: bool = False):
        return cls(
            step=jnp.int32(0),
            params=params,
            opt_state=tx.init(params),
            batch_stats=batch_stats if batch_stats is not None else {},
            dynamic_scale=dynamic_scale,
            ema_params=params if (ema or swa) else None,
            swa_count=jnp.int32(0) if swa else None,
        )
